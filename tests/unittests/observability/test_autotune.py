"""Closed-loop sync autotuning: the observe → candidate → trial → commit |
rollback state machine, its health-monitor/divergence guardrails, the
trace-safety audit (cadence commits retrace-free, compression commits cost
exactly one ledgered new-key miss), and the three observability surfaces —
flight-recorded ``policy`` events, the JSONL decision ledger through the
export front door, and the ``tm_tpu_autotune_*`` Prometheus families."""

import io
import json

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import NUM_DEVICES
from torchmetrics_tpu import observability as obs
from torchmetrics_tpu.classification import BinaryCalibrationError, MulticlassAccuracy
from torchmetrics_tpu.core.compile import cache_stats
from torchmetrics_tpu.observability import tracing
from torchmetrics_tpu.observability.export import SCHEMA_VERSION, parse_export_line
from torchmetrics_tpu.parallel import (
    SyncAdvisor,
    SyncAutotuner,
    SyncPolicy,
    SyncStepper,
    committed_policy,
    policy_dict,
)
from torchmetrics_tpu.parallel.autotune import (
    AUTOTUNE_ACTIONS,
    AUTOTUNE_STATES,
    LEDGER_KIND,
)
from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

pytestmark = pytest.mark.autotune


def _metric():
    return MulticlassAccuracy(num_classes=5, average="micro")


def _batch(rng, n=16):
    return (
        jnp.asarray(rng.integers(0, 5, (n,))),
        jnp.asarray(rng.integers(0, 5, (n,))),
    )


def _calib():
    # 2 x (1024,) float32 states = 4096-byte bucket: clears the compression
    # floor, so a bf16/int8 policy genuinely changes the lowered sync
    return BinaryCalibrationError(n_bins=1024)


def _calib_batch(rng, n=16):
    return (
        jnp.asarray(rng.random((n,), dtype=np.float32)),
        jnp.asarray(rng.integers(0, 2, (n,))),
    )


def _run(n, sync_s, steps=8):
    return {
        "every_n": n,
        "steps": steps,
        "rounds": 1,
        "syncs": steps // n,
        "sync_s": sync_s,
        "mean_sync_s": sync_s / max(steps // n, 1),
        "sync_wire_bytes": 4096,
        "sync_raw_bytes": 4096,
        "mean_sync_bytes": 512.0,
    }


def _profile(*runs):
    """A deterministic prebuilt profile — tests drive the state machine on
    known measurements instead of CPU wall-clock noise."""
    return {
        "steps": 8,
        "n_devices": NUM_DEVICES,
        "runs": list(runs),
        "buckets": {},
    }


#: every_n=1 takes 1.0s of sync, every_n=4 cuts it 4x: propose() picks 4
FOUR_X = (_run(1, 1.0), _run(4, 0.25))


def _tuner(mesh, metric=None, policy=None, **kw):
    m = metric if metric is not None else _metric()
    stepper = SyncStepper(
        m, mesh=mesh, policy=policy if policy is not None else SyncPolicy()
    )
    kw.setdefault("candidates", (1, 4))
    return SyncAutotuner(stepper, **kw), stepper


# ------------------------------------------------------- satellite: baseline
def test_advisor_rejects_baseline_less_candidates(mesh):
    with pytest.raises(ValueError, match="must include 1"):
        SyncAdvisor(_metric(), mesh=mesh, candidates=(4,))


def test_profile_always_measures_the_baseline(mesh):
    """Even when the candidate list is mangled after construction (config
    override, deserialized state), profile() still measures every_n=1 —
    every recommendation is judged against the every-step baseline."""
    advisor = SyncAdvisor(_metric(), mesh=mesh, candidates=(1, 4))
    advisor.candidates = (4,)
    rng = np.random.default_rng(0)
    profile = advisor.profile(*_batch(rng), steps=4, rounds=1)
    assert [r["every_n"] for r in profile["runs"]] == [1, 4]
    rec = advisor.recommend(target_cut=1.0)
    assert rec["baseline_sync_s"] > 0.0


def test_advisor_accepts_advice_only_error_budget(mesh):
    """A budget WITHOUT a compression mode declares the tolerance the
    compression advice is judged against — the profile runs exact."""
    advisor = SyncAdvisor(_calib(), mesh=mesh, candidates=(1, 4), error_budget=5e-2)
    advisor._profile = _profile(*FOUR_X)
    comp = advisor.recommend(target_cut=3.5)["compression"]
    assert comp["mode"] == "none" and comp["error_budget"] == 5e-2
    assert comp["recommended_mode"] in ("bf16", "int8")


def test_recommend_without_baseline_raises_clearly(mesh):
    """A hand-built/deserialized profile missing the every_n=1 row fails with
    a RuntimeError that names the problem — not a bare StopIteration."""
    advisor = SyncAdvisor(_metric(), mesh=mesh)
    advisor._profile = _profile(_run(4, 0.25))
    with pytest.raises(RuntimeError, match="no every_n == 1 baseline"):
        advisor.recommend(target_cut=2.0)


# ----------------------------------------------------------- state machine
def test_happy_path_report_only_by_default(mesh):
    tuner, stepper = _tuner(mesh)
    assert tuner.report_only and tuner.state == "observe"
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    assert tuner.state == "candidate"
    assert tuner.candidate()["policy"]["every_n"] == 4
    tuner.arm()
    assert tuner.state == "trial"
    entry = tuner.commit()
    assert tuner.state == "committed"
    # report-only: the decision is ledgered but nothing is touched
    assert entry["applied"] is False
    assert stepper.policy == SyncPolicy()
    assert committed_policy(stepper.target) is None
    assert [e["action"] for e in tuner.decision_ledger()] == [
        "observe",
        "propose",
        "arm",
        "commit",
    ]
    assert all(e["state_to"] in AUTOTUNE_STATES for e in tuner.decision_ledger())


def test_arm_and_commit_enforce_order(mesh):
    tuner, _ = _tuner(mesh)
    with pytest.raises(RuntimeError, match="no candidate"):
        tuner.arm()
    with pytest.raises(RuntimeError, match="no staged trial"):
        tuner.commit()


def test_commit_applies_cadence_with_zero_retraces(mesh):
    """An applied every_n commit switches the live stepper mid-stream and the
    compile-cache delta since the commit is empty — cadence is host-side."""
    tuner, stepper = _tuner(mesh, report_only=False)
    rng = np.random.default_rng(1)
    for _ in range(3):  # compile the cadence step + sync pre-commit
        stepper.update(*_batch(rng))
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    entry = tuner.commit()
    assert entry["applied"] is True
    assert entry["expected_retraces"] == {
        "new_keys": 0,
        "cause": None,
        "entrypoint": None,
    }
    assert stepper.policy.every_n_steps == 4
    assert committed_policy(stepper.target).every_n_steps == 4
    for _ in range(8):  # two full windows under the committed cadence
        stepper.update(*_batch(rng))
    audit = tuner.retrace_report()
    assert audit["ok"], audit
    assert audit["extra_misses"] == 0 and audit["miss_causes"] == {}
    # the audit itself is a ledgered decision
    assert tuner.decision_ledger()[-1]["action"] == "audit"


def test_compression_commit_costs_exactly_one_new_key(mesh):
    """A compression change re-keys the cadence sync: the audit attributes
    exactly one new-key miss and nothing else."""
    tuner, stepper = _tuner(
        mesh, metric=_calib(), report_only=False, error_budget=5e-2
    )
    rng = np.random.default_rng(2)
    for _ in range(2):  # compile the exact-mode step + sync pre-commit
        stepper.update(*_calib_batch(rng))
    stepper.sync()
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    mode = tuner.candidate()["policy"]["compression"]
    assert mode in ("bf16", "int8")  # budget of 5e-2 admits a quantized mode
    tuner.arm()
    entry = tuner.commit()
    assert entry["expected_retraces"] == {
        "new_keys": 1,
        "cause": "new-key",
        "entrypoint": "cadence",
    }
    assert stepper.policy.compression == mode
    for _ in range(4):  # one full window: first sync under the new mode
        stepper.update(*_calib_batch(rng))
    audit = tuner.retrace_report()
    assert audit["ok"], audit
    assert audit["extra_misses"] == 1
    assert audit["miss_causes"] == {"new-key": 1}


def test_compression_commit_flushes_the_open_window(mesh):
    """Steps accumulated under the exact mode sync under the exact mode —
    the policy switch flushes them rather than re-keying them mid-window."""
    tuner, stepper = _tuner(
        mesh,
        metric=_calib(),
        policy=SyncPolicy(every_n_steps=4),
        report_only=False,
        error_budget=5e-2,
    )
    rng = np.random.default_rng(3)
    stepper.update(*_calib_batch(rng))
    assert stepper.pending == 1
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    assert stepper.pending == 0  # the open window was flushed pre-switch


def test_report_only_commit_refuses_retrace_report(mesh):
    tuner, _ = _tuner(mesh)
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    with pytest.raises(RuntimeError, match="no applied commit"):
        tuner.retrace_report()


# --------------------------------------------------------------- guardrails
def _alerting_monitor(tuner, series="loss"):
    monitor = obs.HealthMonitor()
    monitor.watch(series, obs.NonFiniteRule(severity="critical"))
    monitor.add_sink(tuner.guardrail_sink())
    return monitor


def test_health_alert_vetoes_pending_trial(mesh):
    obs.enable()
    tuner, stepper = _tuner(mesh, report_only=False)
    monitor = _alerting_monitor(tuner)
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    monitor.observe("loss", float("nan"), step=7)
    # the alert landed in-band: trial vetoed before it ever applied
    assert tuner.state == "observe"
    assert stepper.policy == SyncPolicy()
    assert committed_policy(stepper.target) is None
    assert tuner.counts["vetoes"] == 1
    with pytest.raises(RuntimeError, match="vetoed"):
        tuner.commit()
    veto = next(e for e in tuner.decision_ledger() if e["action"] == "veto")
    assert veto["state_from"] == "trial" and veto["state_to"] == "observe"
    assert veto["alert"]["kind"] == "health_alert"
    assert veto["alert"]["series"] == "loss"
    assert veto["new_policy"]["every_n"] == 4  # what was vetoed, on the record


def test_health_alert_rolls_back_committed_policy(mesh):
    obs.enable()
    tuner, stepper = _tuner(mesh, report_only=False)
    monitor = _alerting_monitor(tuner)
    rng = np.random.default_rng(4)
    stepper.update(*_batch(rng))
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    assert stepper.policy.every_n_steps == 4
    monitor.observe("loss", float("inf"), step=11)
    # committed policy rolled back to the pre-commit one, in-band
    assert tuner.state == "observe"
    assert stepper.policy == SyncPolicy()
    assert committed_policy(stepper.target) == SyncPolicy()
    assert tuner.counts["rollbacks"] == 1
    rb = next(e for e in tuner.decision_ledger() if e["action"] == "rollback")
    assert rb["applied"] is True
    assert rb["old_policy"]["every_n"] == 4
    assert rb["new_policy"]["every_n"] == 1  # every-step default restored
    assert rb["alert"]["severity"] == "critical"


def test_alert_below_veto_severity_is_ignored(mesh):
    obs.enable()
    tuner, stepper = _tuner(mesh, report_only=False, veto_severity="critical")
    monitor = obs.HealthMonitor()
    monitor.watch("loss", obs.NonFiniteRule(severity="warning"))
    monitor.add_sink(tuner.guardrail_sink())
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    monitor.observe("loss", float("nan"), step=0)
    assert tuner.state == "trial"  # warning < critical: no veto
    tuner.commit()
    assert stepper.policy.every_n_steps == 4


def test_divergence_vetoes_trial_and_rolls_back_commit(mesh):
    tuner, stepper = _tuner(mesh, report_only=False)
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    entry = tuner.report_divergence(ReplicaDivergenceError("replica 3 drifted"))
    assert entry["action"] == "veto" and "replica 3 drifted" in entry["error"]
    assert tuner.state == "observe"
    # ...and again for a committed policy: divergence rolls it back
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    assert stepper.policy.every_n_steps == 4
    entry = tuner.report_divergence(ReplicaDivergenceError("replica 5 drifted"))
    assert entry["action"] == "rollback"
    assert stepper.policy == SyncPolicy()
    # nothing staged, nothing committed: the verifier report is a no-op
    assert tuner.report_divergence(ReplicaDivergenceError("idle")) is None


# --------------------------------------- satellite: snapshot across transition
def test_snapshot_restore_across_mid_window_policy_transition(mesh):
    """A snapshot taken mid-window after an every_n commit restores into a
    fresh stepper with no samples lost or double-counted, and the restored
    stepper honors the committed cadence."""
    rng = np.random.default_rng(5)
    batches = [_batch(rng) for _ in range(8)]
    m = _metric()
    stepper = SyncStepper(m, mesh=mesh, policy=SyncPolicy(every_n_steps=8))
    tuner = SyncAutotuner(stepper, report_only=False, candidates=(1, 4))
    for b in batches[:3]:
        stepper.update(*b)  # window open: 3 pending steps
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    assert stepper.policy.every_n_steps == 4
    snap = stepper.snapshot()
    assert snap["pending"] == 3  # the open window rode the transition

    restored = SyncStepper(
        _metric(), mesh=mesh, policy=committed_policy(m) or stepper.policy
    )
    restored.restore(snap)
    assert restored.pending == 3 and restored.steps == 3
    # the very next update closes the committed 4-step window
    restored.update(*batches[3])
    assert restored.pending == 0
    for b in batches[4:]:
        restored.update(*b)
    # ground truth: every batch exactly once
    ref = _metric()
    state = ref.init_state()
    for b in batches:
        state = ref.update_state(state, *b)
    assert float(restored.compute()) == pytest.approx(
        float(ref.compute_state(state))
    )


# --------------------------------------------- satellite: export front door
def test_ledger_exports_through_front_door_and_parses_back(mesh):
    tuner, _ = _tuner(mesh, report_only=False)
    monitor = _alerting_monitor(tuner)
    obs.enable()
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    monitor.observe("loss", float("nan"), step=3)  # rollback, on the ledger
    buf = io.StringIO()
    lines = tuner.export_ledger(stream=buf)
    assert buf.getvalue().splitlines() == lines
    parsed = [parse_export_line(line) for line in lines]
    assert [p["action"] for p in parsed] == [
        "observe",
        "propose",
        "arm",
        "commit",
        "rollback",
    ]
    for p in parsed:
        assert p["kind"] == LEDGER_KIND
        assert p["action"] in AUTOTUNE_ACTIONS
        assert p["schema_version"] == SCHEMA_VERSION
        assert isinstance(p["process"]["index"], int)


def test_recommendation_exports_through_front_door(mesh):
    """SyncAdvisor.recommend lines ride the same JSONL front door: kind
    stamp, schema version, process identity, all parse back."""
    advisor = SyncAdvisor(_metric(), mesh=mesh, candidates=(1, 4))
    advisor._profile = _profile(*FOUR_X)
    rec = advisor.recommend(target_cut=3.5)
    buf = io.StringIO()
    line = obs.export(rec, fmt="jsonl", stream=buf)
    parsed = parse_export_line(line)
    assert parsed["kind"] == "sync_advice"
    assert parsed["every_n"] == 4
    assert parsed["schema_version"] == SCHEMA_VERSION
    assert isinstance(parsed["process"]["index"], int)


def test_flight_recorder_policy_category_events(mesh):
    obs.enable()
    tracing.start(capacity=256)
    try:
        tuner, _ = _tuner(mesh, report_only=False)
        tuner.observe(profile=_profile(*FOUR_X))
        tuner.propose()
        tuner.arm()
        tuner.commit()
        tuner.rollback(reason="manual")
        policy_events = [e for e in tracing.events() if e.cat == "policy"]
        assert [e.name for e in policy_events] == [
            "policy/observe",
            "policy/propose",
            "policy/arm",
            "policy/commit",
            "policy/rollback",
        ]
        commit = policy_events[3]
        assert commit.args["new_policy"]["every_n"] == 4
        assert commit.args["applied"] is True
        assert commit.args["rationale"]
    finally:
        tracing.stop()


def test_policy_events_dark_when_disabled(mesh):
    """Off-by-default telemetry: a disarmed/disabled run ledgers decisions
    but records no flight-recorder events."""
    tuner, _ = _tuner(mesh)
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    assert tracing.events() == []
    assert len(tuner.decision_ledger()) == 2  # the ledger is always on


def test_prometheus_autotune_families(mesh):
    obs.enable()
    tuner, _ = _tuner(mesh, report_only=False)
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    report = obs.registry.report()
    report["autotune"] = tuner.report()
    text = obs.export(report, fmt="prometheus")
    assert 'tm_tpu_autotune_policy_info{' in text
    assert 'every_n="4"' in text and 'state="committed"' in text
    assert 'tm_tpu_autotune_transitions_total{action="commits"' in text
    assert "tm_tpu_autotune_vetoes_total" in text
    assert "tm_tpu_autotune_rollbacks_total" in text


def test_policy_counters_on_target_telemetry(mesh):
    obs.enable()
    tuner, stepper = _tuner(mesh, report_only=False)
    tuner.observe(profile=_profile(*FOUR_X))
    tuner.propose()
    tuner.arm()
    tuner.commit()
    tuner.rollback(reason="manual")
    counters = obs.registry.telemetry_for(stepper.target).as_dict()["counters"]
    assert counters["policy_commits"] == 1
    assert counters["policy_rollbacks"] == 1
    assert counters["policy_vetoes"] == 0
