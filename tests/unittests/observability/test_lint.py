"""Library hygiene lints over the ``torchmetrics_tpu/`` AST.

* No bare ``print(``: user-facing output must go through the
  ``torchmetrics_tpu`` logger (which carries a ``NullHandler`` — see
  ``utilities/prints.py``) or the rank-zero helpers, never stdout.  Allowed
  exceptions: ``utilities/prints.py`` itself and ``utilities/plot.py``
  (interactive plotting helper).
* No direct ``jax.lax.psum``/``all_gather`` outside ``core/reductions.py``
  and ``parallel/coalesce.py``: every cross-device collective must go
  through ``sync_leaf`` or the coalescing planner so it is bucketed,
  telemetry-counted, and covered by the byte-cost model.  A stray direct
  collective silently escapes all three.
"""

import ast
from pathlib import Path

PACKAGE = Path(__file__).resolve().parents[3] / "torchmetrics_tpu"
ALLOWED = {"utilities/prints.py", "utilities/plot.py", "plot.py"}

#: attribute names whose direct call is a collective launch
BANNED_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather"}
#: the only modules allowed to lower collectives themselves
COLLECTIVE_ALLOWED = {"core/reductions.py", "parallel/coalesce.py"}


def _bare_prints(path: Path):
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            yield node.lineno


def test_package_importable_from_expected_location():
    assert PACKAGE.is_dir(), f"package not found at {PACKAGE}"
    assert (PACKAGE / "__init__.py").is_file()


def test_no_bare_print_in_library():
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        rel = path.relative_to(PACKAGE).as_posix()
        if rel in ALLOWED:
            continue
        offenders.extend(f"{rel}:{lineno}" for lineno in _bare_prints(path))
    assert not offenders, (
        "bare print() calls found (route output through the torchmetrics_tpu "
        f"logger or utilities.prints helpers instead): {offenders}"
    )


def _direct_collectives(path: Path):
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # jax.lax.psum(...) style            from jax.lax import psum; psum(...)
        name = func.attr if isinstance(func, ast.Attribute) else getattr(func, "id", None)
        if name in BANNED_COLLECTIVES:
            yield node.lineno, name


def test_no_direct_collectives_outside_reduction_layer():
    """Every cross-device collective must lower through core/reductions.py's
    ``sync_leaf`` or the parallel/coalesce.py planner — anywhere else it
    escapes bucketing, the telemetry ``collectives`` counter, and the
    sync-byte cost model."""
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        rel = path.relative_to(PACKAGE).as_posix()
        if rel in COLLECTIVE_ALLOWED:
            continue
        offenders.extend(f"{rel}:{lineno} ({name})" for lineno, name in _direct_collectives(path))
    assert not offenders, (
        "direct collective calls found outside core/reductions.py and "
        f"parallel/coalesce.py (use sync_leaf or the coalescing planner): {offenders}"
    )
