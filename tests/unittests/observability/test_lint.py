"""Package-wide lint gate — thin shim over ``torchmetrics_tpu.analysis``.

The ad-hoc AST walks that used to live here (bare ``print``, direct
``jax.lax`` collectives) are now registered rules TMT001/TMT002 of the
analysis framework, alongside the trace-safety rules TMT003+.  This file
just asserts the package is clean under the full registry — the CLI
(``python -m torchmetrics_tpu.analysis``) is exercised separately in
``tests/unittests/analysis/test_cli.py``.
"""

import pytest

from torchmetrics_tpu.analysis import all_rules, lint_package

pytestmark = pytest.mark.lint


def test_rule_registry_has_full_surface():
    ids = [r.id for r in all_rules()]
    assert len(ids) >= 8, f"expected >=8 registered rules, got {ids}"
    # the two legacy checks must have survived the migration
    assert "TMT001" in ids  # bare print
    assert "TMT002" in ids  # direct collectives outside reductions/coalesce


def test_package_lints_clean():
    findings = lint_package()
    assert findings == [], "\n".join(f.location() for f in findings)
