"""Library hygiene lint: no bare ``print(`` inside ``torchmetrics_tpu/``.

User-facing output must go through the ``torchmetrics_tpu`` logger (which
carries a ``NullHandler`` — see ``utilities/prints.py``) or the rank-zero
helpers, never stdout.  Allowed exceptions: ``utilities/prints.py`` itself
and ``utilities/plot.py`` (interactive plotting helper).
"""

import ast
from pathlib import Path

PACKAGE = Path(__file__).resolve().parents[3] / "torchmetrics_tpu"
ALLOWED = {"utilities/prints.py", "utilities/plot.py", "plot.py"}


def _bare_prints(path: Path):
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            yield node.lineno


def test_package_importable_from_expected_location():
    assert PACKAGE.is_dir(), f"package not found at {PACKAGE}"
    assert (PACKAGE / "__init__.py").is_file()


def test_no_bare_print_in_library():
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        rel = path.relative_to(PACKAGE).as_posix()
        if rel in ALLOWED:
            continue
        offenders.extend(f"{rel}:{lineno}" for lineno in _bare_prints(path))
    assert not offenders, (
        "bare print() calls found (route output through the torchmetrics_tpu "
        f"logger or utilities.prints helpers instead): {offenders}"
    )
