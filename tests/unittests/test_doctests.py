"""Executable docstring examples (VERDICT r3 #10).

The reference runs every metric docstring as a test (``--doctest-modules``,
reference Makefile:28, pyproject.toml:116-121).  Here each module carrying
``Example::`` blocks is doctested explicitly, and the runner asserts the
examples were actually FOUND — a renamed class or dedented block cannot
silently drop coverage.
"""

import doctest
import importlib

import pytest

# module -> minimum number of doctest examples expected in it
DOCTEST_MODULES = {
    "torchmetrics_tpu.classification.accuracy": 2,
    "torchmetrics_tpu.classification.f_beta": 2,
    "torchmetrics_tpu.classification.auroc": 2,
    "torchmetrics_tpu.classification.average_precision": 1,
    "torchmetrics_tpu.classification.confusion_matrix": 1,
    "torchmetrics_tpu.classification.cohen_kappa": 1,
    "torchmetrics_tpu.classification.matthews_corrcoef": 1,
    "torchmetrics_tpu.regression.errors": 5,
    "torchmetrics_tpu.regression.variance": 2,
    "torchmetrics_tpu.regression.correlation": 3,
    "torchmetrics_tpu.image.psnr": 1,
    "torchmetrics_tpu.text.bleu": 2,
    "torchmetrics_tpu.text.asr": 3,
    "torchmetrics_tpu.retrieval.metrics": 3,
    "torchmetrics_tpu.aggregation": 3,
    "torchmetrics_tpu.nominal.nominal": 2,
    "torchmetrics_tpu.clustering.extrinsic": 2,
    "torchmetrics_tpu.segmentation.mean_iou": 1,
    "torchmetrics_tpu.segmentation.generalized_dice": 1,
    "torchmetrics_tpu.audio.metrics": 3,
    "torchmetrics_tpu.image.spectral": 1,
    "torchmetrics_tpu.text.rouge": 1,
    "torchmetrics_tpu.text.ter": 1,
    "torchmetrics_tpu.regression.distribution": 1,
    "torchmetrics_tpu.wrappers.minmax": 1,
    "torchmetrics_tpu.wrappers.classwise": 1,
    "torchmetrics_tpu.wrappers.multioutput": 1,
    "torchmetrics_tpu.wrappers.multitask": 1,
    "torchmetrics_tpu.wrappers.running": 1,
    "torchmetrics_tpu.wrappers.bootstrapping": 1,
    "torchmetrics_tpu.detection.mean_ap": 1,
    "torchmetrics_tpu.detection.iou": 1,
    "torchmetrics_tpu.classification.specificity": 1,
    "torchmetrics_tpu.classification.precision_recall": 2,
    "torchmetrics_tpu.classification.hamming": 1,
    "torchmetrics_tpu.classification.jaccard": 1,
    "torchmetrics_tpu.classification.calibration_error": 1,
    "torchmetrics_tpu.classification.exact_match": 1,
    "torchmetrics_tpu.image.ssim": 1,
    "torchmetrics_tpu.clustering.intrinsic": 2,
    "torchmetrics_tpu.functional.pairwise.pairwise": 2,
    "torchmetrics_tpu.collections": 1,
    "torchmetrics_tpu.classification.stat_scores": 1,
    "torchmetrics_tpu.text.chrf": 1,
}


@pytest.mark.parametrize("module_name", sorted(DOCTEST_MODULES))
def test_module_doctests(module_name):
    module = importlib.import_module(module_name)
    finder = doctest.DocTestFinder(exclude_empty=True)
    runner = doctest.DocTestRunner(optionflags=doctest.NORMALIZE_WHITESPACE)
    n_classes_with_examples = 0
    for test in finder.find(module, module_name):
        if not test.examples:
            continue
        n_classes_with_examples += 1
        runner.run(test)
    assert n_classes_with_examples >= DOCTEST_MODULES[module_name], (
        f"{module_name}: expected >= {DOCTEST_MODULES[module_name]} docstring examples, "
        f"found {n_classes_with_examples} — example blocks lost?"
    )
    results = runner.summarize(verbose=False)
    assert results.failed == 0, f"{module_name}: {results.failed} doctest failure(s)"
