"""Executable docstring examples — auto-discovered, universally required.

The reference executes an example in *every* metric file via
``--doctest-modules`` (reference Makefile:28, pyproject.toml:116-121, 314
files).  This runner goes further than r4's hand-enumerated dict (VERDICT r4
weak #6: a new module silently got no coverage): it WALKS the package, runs
every doctest it finds, and *fails* any module that defines a public metric
class or a publicly exported functional but carries zero examples.
"""

import doctest
import importlib
import pkgutil

import pytest

import torchmetrics_tpu
from torchmetrics_tpu.core.metric import Metric

# Infrastructure modules where examples are not *required* (doctests found in
# them still run).  Everything else that defines public metrics/functionals
# must carry at least one example.
EXEMPT = {
    # abstract bases / plumbing with no user-facing entry point
    "torchmetrics_tpu.classification.base",
    "torchmetrics_tpu.retrieval.base",
    "torchmetrics_tpu.wrappers.abstract",
    "torchmetrics_tpu.core.composition",  # built via Metric dunders, exemplified in core.metric
    # model backbones (exercised via their metrics)
    "torchmetrics_tpu.image.backbones.inception",
    "torchmetrics_tpu.image.backbones.lpips_net",
    "torchmetrics_tpu.multimodal.backbones.clip",
    # internal helpers without public API surface
    "torchmetrics_tpu.functional.clustering.utils",
    "torchmetrics_tpu.functional.nominal.utils",
    "torchmetrics_tpu.functional.image.helper",
    "torchmetrics_tpu.functional.text.helper",
    "torchmetrics_tpu.functional.detection.matcher",
    "torchmetrics_tpu.utilities.imports",
    "torchmetrics_tpu.utilities.prints",
    "torchmetrics_tpu.utilities.exceptions",
    "torchmetrics_tpu.utilities.plot",
    "torchmetrics_tpu.utilities.benchmark",
}


# Per-module floors carried over from the r4 enumerated runner: modules known
# to hold MANY examples keep their counts, so a dedent/rename that silently
# drops examples (but leaves >= 1) still fails.  The walk covers everything
# else at a floor of 1.
MIN_EXAMPLES = {
    "torchmetrics_tpu.classification.accuracy": 2,
    "torchmetrics_tpu.classification.f_beta": 2,
    "torchmetrics_tpu.classification.auroc": 2,
    "torchmetrics_tpu.regression.errors": 5,
    "torchmetrics_tpu.regression.variance": 2,
    "torchmetrics_tpu.regression.correlation": 3,
    "torchmetrics_tpu.text.bleu": 2,
    "torchmetrics_tpu.text.asr": 3,
    "torchmetrics_tpu.retrieval.metrics": 3,
    "torchmetrics_tpu.aggregation": 3,
    "torchmetrics_tpu.nominal.nominal": 2,
    "torchmetrics_tpu.clustering.extrinsic": 2,
    "torchmetrics_tpu.clustering.intrinsic": 2,
    "torchmetrics_tpu.audio.metrics": 3,
    "torchmetrics_tpu.classification.precision_recall": 2,
    "torchmetrics_tpu.functional.pairwise.pairwise": 2,
}


def _all_modules():
    names = ["torchmetrics_tpu"]
    for info in pkgutil.walk_packages(torchmetrics_tpu.__path__, "torchmetrics_tpu."):
        if any(part.startswith("_") for part in info.name.split(".")[1:]):
            continue
        names.append(info.name)
    return sorted(names)


ALL_MODULES = _all_modules()


def _functional_exports():
    import torchmetrics_tpu.functional as F

    names = set()
    for name in dir(F):
        obj = getattr(F, name)
        if callable(obj) and not name.startswith("_"):
            names.add(getattr(obj, "__module__", None))
    return names


FUNCTIONAL_DEF_MODULES = _functional_exports()


def _requires_example(module) -> bool:
    """True when the module *defines* a public Metric subclass or a function
    the top-level functional package re-exports."""
    name = module.__name__
    if name in EXEMPT or name.rsplit(".", 1)[-1] == "__init__":
        return False
    # __init__ re-export manifests define nothing themselves
    if hasattr(module, "__path__"):
        return False
    for attr in dir(module):
        if attr.startswith("_"):
            continue
        obj = getattr(module, attr)
        if isinstance(obj, type) and issubclass(obj, Metric) and obj.__module__ == name:
            return True
    return name in FUNCTIONAL_DEF_MODULES


@pytest.mark.parametrize("module_name", ALL_MODULES)
def test_module_doctests(module_name):
    module = importlib.import_module(module_name)
    finder = doctest.DocTestFinder(exclude_empty=True)
    runner = doctest.DocTestRunner(optionflags=doctest.NORMALIZE_WHITESPACE)
    n_with_examples = 0
    for test in finder.find(module, module_name):
        if not test.examples:
            continue
        n_with_examples += 1
        runner.run(test)
    if _requires_example(module):
        floor = MIN_EXAMPLES.get(module_name, 1)
        assert n_with_examples >= floor, (
            f"{module_name} defines public metrics/functionals but has {n_with_examples} "
            f"executable docstring example(s), expected >= {floor} — example blocks lost? "
            "(the reference doctests every metric file via --doctest-modules)"
        )
    results = runner.summarize(verbose=False)
    assert results.failed == 0, f"{module_name}: {results.failed} doctest failure(s)"
