"""Regression metrics through the 8-device sharded-sync path.

The streaming-sufficient-statistic states (Pearson, R2) are the interesting
ones here: their ``merge_states`` does mean-correction math that a naive
psum would get wrong, so mesh parity is a real check, not a tautology.
"""

import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity

N = 64


@pytest.fixture()
def xy():
    rng = np.random.default_rng(5)
    preds = rng.normal(size=(2, N)).astype(np.float32)
    target = (preds + 0.3 * rng.normal(size=(2, N))).astype(np.float32)
    return preds, target


def _batches(preds, target):
    return [(preds[0], target[0]), (preds[1], target[1])]


def test_sharded_mse(mesh, xy):
    from sklearn.metrics import mean_squared_error

    from torchmetrics_tpu.regression import MeanSquaredError

    preds, target = xy
    oracle = mean_squared_error(target.ravel(), preds.ravel())
    assert_sharded_parity(mesh, MeanSquaredError, _batches(preds, target), oracle=oracle)


def test_sharded_mae(mesh, xy):
    from sklearn.metrics import mean_absolute_error

    from torchmetrics_tpu.regression import MeanAbsoluteError

    preds, target = xy
    oracle = mean_absolute_error(target.ravel(), preds.ravel())
    assert_sharded_parity(mesh, MeanAbsoluteError, _batches(preds, target), oracle=oracle)


def test_sharded_pearson(mesh, xy):
    from scipy.stats import pearsonr

    from torchmetrics_tpu.regression import PearsonCorrCoef

    preds, target = xy
    oracle = pearsonr(preds.ravel(), target.ravel()).statistic
    assert_sharded_parity(
        mesh, PearsonCorrCoef, _batches(preds, target), oracle=oracle, atol=1e-4, rtol=1e-4
    )


def test_sharded_r2(mesh, xy):
    from sklearn.metrics import r2_score

    from torchmetrics_tpu.regression import R2Score

    preds, target = xy
    oracle = r2_score(target.ravel(), preds.ravel())
    assert_sharded_parity(mesh, R2Score, _batches(preds, target), oracle=oracle, atol=1e-4, rtol=1e-4)


def test_sharded_spearman_cat_state(mesh, xy):
    """Spearman keeps raw cat states (rank transform needs the full sample)."""
    from scipy.stats import spearmanr

    from torchmetrics_tpu.regression import SpearmanCorrCoef

    preds, target = xy
    oracle = spearmanr(preds.ravel(), target.ravel()).statistic
    assert_sharded_parity(
        mesh, SpearmanCorrCoef, _batches(preds, target), oracle=oracle, atol=1e-4, rtol=1e-4
    )
