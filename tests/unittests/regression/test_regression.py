"""Regression metric tests vs sklearn/scipy."""

import numpy as np
import jax.numpy as jnp
import pytest
from scipy import stats
from sklearn import metrics as skm

from tests.helpers.testers import run_class_metric_test

from torchmetrics_tpu.regression import (
    ConcordanceCorrCoef,
    CosineSimilarity,
    CriticalSuccessIndex,
    ExplainedVariance,
    KendallRankCorrCoef,
    KLDivergence,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MinkowskiDistance,
    PearsonCorrCoef,
    R2Score,
    RelativeSquaredError,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from torchmetrics_tpu.functional.regression import (
    kendall_rank_corrcoef,
    pearson_corrcoef,
    spearman_corrcoef,
)

N_BATCHES, BATCH = 4, 32
rng = np.random.default_rng(11)
PREDS = rng.normal(size=(N_BATCHES, BATCH)).astype(np.float32)
TARGET = (PREDS + 0.5 * rng.normal(size=(N_BATCHES, BATCH))).astype(np.float32)
POS_PREDS = np.abs(PREDS) + 0.1
POS_TARGET = np.abs(TARGET) + 0.1


@pytest.mark.parametrize("factory,ref,preds,target", [
    (lambda: MeanSquaredError(), lambda p, t: skm.mean_squared_error(t, p), PREDS, TARGET),
    (lambda: MeanSquaredError(squared=False), lambda p, t: np.sqrt(skm.mean_squared_error(t, p)), PREDS, TARGET),
    (lambda: MeanAbsoluteError(), lambda p, t: skm.mean_absolute_error(t, p), PREDS, TARGET),
    (lambda: MeanAbsolutePercentageError(), lambda p, t: skm.mean_absolute_percentage_error(t, p), POS_PREDS, POS_TARGET),
    (lambda: MeanSquaredLogError(), lambda p, t: skm.mean_squared_log_error(t, p), POS_PREDS, POS_TARGET),
    (lambda: R2Score(), lambda p, t: skm.r2_score(t, p), PREDS, TARGET),
    (lambda: ExplainedVariance(), lambda p, t: skm.explained_variance_score(t, p), PREDS, TARGET),
    (lambda: TweedieDevianceScore(power=0.0), lambda p, t: skm.mean_tweedie_deviance(t, p, power=0), PREDS, TARGET),
    (lambda: TweedieDevianceScore(power=1.0), lambda p, t: skm.mean_tweedie_deviance(t, p, power=1), POS_PREDS, POS_TARGET),
    (lambda: TweedieDevianceScore(power=2.0), lambda p, t: skm.mean_tweedie_deviance(t, p, power=2), POS_PREDS, POS_TARGET),
    (lambda: PearsonCorrCoef(), lambda p, t: stats.pearsonr(t, p)[0], PREDS, TARGET),
    (lambda: SpearmanCorrCoef(), lambda p, t: stats.spearmanr(t, p)[0], PREDS, TARGET),
    (lambda: KendallRankCorrCoef(), lambda p, t: stats.kendalltau(t, p)[0], PREDS, TARGET),
])
def test_regression_vs_reference(factory, ref, preds, target):
    run_class_metric_test(factory, preds, target, ref, atol=1e-4)


def test_symmetric_mape():
    p, t = POS_PREDS.reshape(-1), POS_TARGET.reshape(-1)
    m = SymmetricMeanAbsolutePercentageError()
    m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.mean(2 * np.abs(p - t) / (np.abs(p) + np.abs(t)))
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-5)


def test_weighted_mape():
    p, t = POS_PREDS.reshape(-1), POS_TARGET.reshape(-1)
    m = WeightedMeanAbsolutePercentageError()
    m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.sum(np.abs(p - t)) / np.sum(np.abs(t))
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-5)


def test_log_cosh():
    p, t = PREDS.reshape(-1), TARGET.reshape(-1)
    m = LogCoshError()
    m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.mean(np.log(np.cosh(p - t)))
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-4)


def test_minkowski():
    p, t = PREDS.reshape(-1), TARGET.reshape(-1)
    m = MinkowskiDistance(p=3)
    m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.sum(np.abs(p - t) ** 3) ** (1 / 3)
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-4)


def test_rse():
    p, t = PREDS.reshape(-1), TARGET.reshape(-1)
    m = RelativeSquaredError()
    m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.sum((t - p) ** 2) / np.sum((t - t.mean()) ** 2)
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-4)


def test_concordance():
    p, t = PREDS.reshape(-1), TARGET.reshape(-1)
    m = ConcordanceCorrCoef()
    m.update(jnp.asarray(p), jnp.asarray(t))
    sx, sy = p.var(), t.var()
    ccc = 2 * np.cov(p, t, bias=True)[0, 1] / (sx + sy + (p.mean() - t.mean()) ** 2)
    np.testing.assert_allclose(float(m.compute()), ccc, rtol=1e-4)


def test_kl_divergence():
    p = np.abs(rng.normal(size=(16, 8))).astype(np.float32)
    q = np.abs(rng.normal(size=(16, 8))).astype(np.float32)
    p /= p.sum(1, keepdims=True)
    q /= q.sum(1, keepdims=True)
    m = KLDivergence()
    m.update(jnp.asarray(p), jnp.asarray(q))
    # KL(p || q): first update argument is the data distribution (reference
    # functional/regression/kl_divergence.py:26-48)
    expected = np.mean([stats.entropy(p[i], q[i]) for i in range(16)])
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-4)


def test_cosine_similarity():
    p = rng.normal(size=(16, 8)).astype(np.float32)
    t = rng.normal(size=(16, 8)).astype(np.float32)
    m = CosineSimilarity(reduction="mean")
    m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.mean([np.dot(p[i], t[i]) / (np.linalg.norm(p[i]) * np.linalg.norm(t[i])) for i in range(16)])
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-4)


def test_csi():
    p = rng.random((64,)).astype(np.float32)
    t = rng.random((64,)).astype(np.float32)
    m = CriticalSuccessIndex(threshold=0.5)
    m.update(jnp.asarray(p), jnp.asarray(t))
    hits = ((p >= 0.5) & (t >= 0.5)).sum()
    misses = ((p < 0.5) & (t >= 0.5)).sum()
    fa = ((p >= 0.5) & (t < 0.5)).sum()
    np.testing.assert_allclose(float(m.compute()), hits / (hits + misses + fa), rtol=1e-5)


def test_pearson_merge_and_sync(mesh):
    """Pearson's custom Welford merge must be exact, incl. in-graph sync."""
    import jax
    from jax.sharding import PartitionSpec as P

    p, t = PREDS.reshape(-1), TARGET.reshape(-1)
    m = PearsonCorrCoef()
    # merge two halves
    s1 = m.update_state(m.init_state(), jnp.asarray(p[:64]), jnp.asarray(t[:64]))
    s2 = m.update_state(m.init_state(), jnp.asarray(p[64:]), jnp.asarray(t[64:]))
    merged = m.merge_states(s1, s2)
    np.testing.assert_allclose(float(m.compute_state(merged)), stats.pearsonr(t, p)[0], rtol=1e-4)

    def step(ps, ts):
        st = m.update_state(m.init_state(), ps, ts)
        return m.sync_states(st, "data")

    from torchmetrics_tpu.core.compile import shard_map

    st = shard_map(step, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P(), check_vma=False)(
        jnp.asarray(p), jnp.asarray(t)
    )
    np.testing.assert_allclose(float(m.compute_state(st)), stats.pearsonr(t, p)[0], rtol=1e-4)


def test_spearman_ties():
    p = np.round(rng.random(100), 1).astype(np.float32)
    t = np.round(rng.random(100), 1).astype(np.float32)
    res = spearman_corrcoef(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(float(res), stats.spearmanr(t, p)[0], rtol=1e-4)


def test_kendall_ties():
    p = np.round(rng.random(50), 1).astype(np.float32)
    t = np.round(rng.random(50), 1).astype(np.float32)
    res = kendall_rank_corrcoef(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(float(res), stats.kendalltau(t, p)[0], rtol=1e-4)
