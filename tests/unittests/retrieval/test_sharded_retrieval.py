"""Retrieval metrics through the 8-device sharded-sync path.

Retrieval states are pure cat states (preds/target/indexes accumulate, the
epoch-end compute segments by query) — sharding splits documents of the
same query across devices, so the all_gather + segment-kernel path is what
makes compute come out right.
"""

import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity

N = 64
N_QUERIES = 6


@pytest.fixture()
def retrieval_inputs():
    rng = np.random.default_rng(11)
    preds = rng.uniform(size=(2, N)).astype(np.float32)
    target = rng.integers(0, 2, size=(2, N))
    indexes = rng.integers(0, N_QUERIES, size=(2, N))
    # every query needs at least one positive doc for MAP/MRR to be defined
    for step in range(2):
        for q in range(N_QUERIES):
            rows = np.nonzero(indexes[step] == q)[0]
            if len(rows) and target[step, rows].sum() == 0:
                target[step, rows[0]] = 1
    return preds, target, indexes


def _batches(preds, target, indexes):
    return [(preds[0], target[0], indexes[0]), (preds[1], target[1], indexes[1])]


@pytest.mark.parametrize("name", ["RetrievalMAP", "RetrievalMRR", "RetrievalNormalizedDCG", "RetrievalHitRate"])
def test_sharded_retrieval(mesh, retrieval_inputs, name):
    import torchmetrics_tpu.retrieval as R

    ctor = getattr(R, name)
    assert_sharded_parity(mesh, ctor, _batches(*retrieval_inputs), atol=1e-5)


def test_sharded_retrieval_map_reference_oracle(mesh, retrieval_inputs):
    """Single-device ≡ sharded ≡ the reference implementation (torch CPU)."""
    from tests.helpers.refpath import require_reference

    require_reference()  # skips when the reference mount / torchmetrics is absent
    torch = pytest.importorskip("torch")
    from torchmetrics.retrieval import RetrievalMAP as RefMAP

    from torchmetrics_tpu.retrieval import RetrievalMAP

    preds, target, indexes = retrieval_inputs
    ref = RefMAP()
    ref.update(
        torch.tensor(preds.ravel()), torch.tensor(target.ravel()).bool(),
        indexes=torch.tensor(indexes.ravel()),
    )
    oracle = float(ref.compute())
    assert_sharded_parity(
        mesh, RetrievalMAP, _batches(preds, target, indexes), oracle=oracle, atol=1e-5
    )
