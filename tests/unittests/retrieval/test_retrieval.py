"""Retrieval metric tests vs per-query numpy references.

The references below re-implement the reference library's per-query semantics
(/root/reference/src/torchmetrics/functional/retrieval/*.py) directly in numpy
with an explicit Python loop — the thing our vectorized kernels must match.
"""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from torchmetrics_tpu.functional.retrieval import (
    retrieval_auroc,
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_precision_recall_curve,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)
from torchmetrics_tpu.retrieval import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRPrecision,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
)

SEED = 42


def _query_data(rng, n_queries=12, min_docs=3, max_docs=14, empty_frac=0.2, graded=False):
    """Variable-length queries, some with no positive target."""
    queries = []
    for q in range(n_queries):
        n = int(rng.integers(min_docs, max_docs + 1))
        preds = rng.random(n)
        if graded:
            target = rng.integers(0, 4, size=n)
        else:
            target = rng.integers(0, 2, size=n)
        if rng.random() < empty_frac:
            target = np.zeros(n, dtype=target.dtype)
        queries.append((preds, target))
    return queries


def _flat(queries):
    preds = np.concatenate([p for p, _ in queries])
    target = np.concatenate([t for _, t in queries])
    indexes = np.concatenate([np.full(len(p), i) for i, (p, _) in enumerate(queries)])
    return preds, target, indexes


# ------------------------------------------------------- numpy per-query refs
def np_precision(p, t, top_k=None, adaptive_k=False):
    n = len(p)
    k = n if top_k is None else top_k
    if adaptive_k:
        k = min(k, n)
    if t.sum() == 0:
        return 0.0
    order = np.argsort(-p, kind="stable")
    return t[order][: min(k, n)].sum() / k


def np_recall(p, t, top_k=None):
    if t.sum() == 0:
        return 0.0
    k = len(p) if top_k is None else top_k
    order = np.argsort(-p, kind="stable")
    return t[order][:k].sum() / t.sum()


def np_hit_rate(p, t, top_k=None):
    k = len(p) if top_k is None else top_k
    order = np.argsort(-p, kind="stable")
    return float(t[order][:k].sum() > 0)


def np_fall_out(p, t, top_k=None):
    k = len(p) if top_k is None else top_k
    neg = 1 - t
    if neg.sum() == 0:
        return 0.0
    order = np.argsort(-p, kind="stable")
    return neg[order][:k].sum() / neg.sum()


def np_average_precision(p, t, top_k=None):
    k = len(p) if top_k is None else min(top_k, len(p))
    order = np.argsort(-p, kind="stable")
    tk = t[order][:k]
    if tk.sum() == 0:
        return 0.0
    positions = np.arange(1, k + 1)[tk > 0]
    return np.mean(np.arange(1, len(positions) + 1) / positions)


def np_reciprocal_rank(p, t, top_k=None):
    k = len(p) if top_k is None else min(top_k, len(p))
    order = np.argsort(-p, kind="stable")
    tk = t[order][:k]
    if tk.sum() == 0:
        return 0.0
    return 1.0 / (np.nonzero(tk)[0][0] + 1)


def np_r_precision(p, t):
    r = int(t.sum())
    if r == 0:
        return 0.0
    order = np.argsort(-p, kind="stable")
    return t[order][:r].sum() / r


def np_ndcg(p, t, top_k=None):
    n = len(p)
    k = n if top_k is None else min(top_k, n)
    disc = 1.0 / np.log2(np.arange(n) + 2.0)
    disc = np.where(np.arange(n) < k, disc, 0.0)
    order = np.argsort(-p, kind="stable")
    dcg = (t[order] * disc).sum()
    idcg = (np.sort(t)[::-1] * disc).sum()
    return 0.0 if idcg == 0 else dcg / idcg


def np_auroc(p, t, top_k=None):
    k = len(p) if top_k is None else min(top_k, len(p))
    order = np.argsort(-p, kind="stable")
    pk, tk = p[order][:k], t[order][:k]
    n_pos, n_neg = tk.sum(), (1 - tk).sum()
    if n_pos == 0 or n_neg == 0:
        return 0.0
    # count pairs (pos, neg) with pos scored higher (+ half credit for ties)
    pos_scores = pk[tk == 1]
    neg_scores = pk[tk == 0]
    wins = (pos_scores[:, None] > neg_scores[None, :]).sum() + 0.5 * (
        pos_scores[:, None] == neg_scores[None, :]
    ).sum()
    return wins / (n_pos * n_neg)


def np_pr_curve(p, t, max_k, adaptive_k=False):
    n = len(p)
    order = np.argsort(-p, kind="stable")
    tk = t[order][: min(max_k, n)].astype(float)
    tk = np.pad(tk, (0, max(0, max_k - n)))
    rel_cum = np.cumsum(tk)
    ks = np.arange(1, max_k + 1)
    denom = np.minimum(ks, n) if adaptive_k else ks
    precision = rel_cum / denom
    recall = rel_cum / t.sum() if t.sum() else np.zeros(max_k)
    if t.sum() == 0:
        precision = np.zeros(max_k)
    return precision, recall


FUNCTIONAL_CASES = [
    (retrieval_precision, np_precision, {}),
    (retrieval_precision, np_precision, {"top_k": 3}),
    (retrieval_precision, np_precision, {"top_k": 100, "adaptive_k": True}),
    (retrieval_recall, np_recall, {}),
    (retrieval_recall, np_recall, {"top_k": 3}),
    (retrieval_hit_rate, np_hit_rate, {"top_k": 2}),
    (retrieval_fall_out, np_fall_out, {"top_k": 3}),
    (retrieval_average_precision, np_average_precision, {}),
    (retrieval_average_precision, np_average_precision, {"top_k": 4}),
    (retrieval_reciprocal_rank, np_reciprocal_rank, {}),
    (retrieval_reciprocal_rank, np_reciprocal_rank, {"top_k": 2}),
    (retrieval_r_precision, np_r_precision, {}),
    (retrieval_normalized_dcg, np_ndcg, {}),
    (retrieval_normalized_dcg, np_ndcg, {"top_k": 4}),
    (retrieval_auroc, np_auroc, {}),
    (retrieval_auroc, np_auroc, {"top_k": 5}),
]


@pytest.mark.parametrize("fn,ref,kwargs", FUNCTIONAL_CASES)
def test_functional_single_query(fn, ref, kwargs):
    rng = np.random.default_rng(SEED)
    for _ in range(8):
        n = int(rng.integers(3, 20))
        preds = rng.random(n)
        target = rng.integers(0, 2, size=n)
        got = float(fn(jnp.asarray(preds), jnp.asarray(target), **kwargs))
        want = float(ref(preds, target, **kwargs))
        assert got == pytest.approx(want, abs=1e-5), (kwargs, preds, target)


def test_functional_pr_curve():
    rng = np.random.default_rng(SEED)
    for adaptive in (False, True):
        n = 10
        preds = rng.random(n)
        target = rng.integers(0, 2, size=n)
        prec, rec, topk = retrieval_precision_recall_curve(
            jnp.asarray(preds), jnp.asarray(target), max_k=6, adaptive_k=adaptive
        )
        ref_p, ref_r = np_pr_curve(preds, target, 6, adaptive)
        np.testing.assert_allclose(np.asarray(prec), ref_p, atol=1e-5)
        np.testing.assert_allclose(np.asarray(rec), ref_r, atol=1e-5)


CLASS_CASES = [
    (RetrievalMAP, np_average_precision, {}),
    (RetrievalMAP, np_average_precision, {"top_k": 3}),
    (RetrievalMRR, np_reciprocal_rank, {}),
    (RetrievalPrecision, np_precision, {"top_k": 3}),
    (RetrievalPrecision, np_precision, {"top_k": 20, "adaptive_k": True}),
    (RetrievalRecall, np_recall, {"top_k": 3}),
    (RetrievalHitRate, np_hit_rate, {"top_k": 2}),
    (RetrievalRPrecision, np_r_precision, {}),
    (RetrievalNormalizedDCG, np_ndcg, {}),
    (RetrievalNormalizedDCG, np_ndcg, {"top_k": 4}),
    (RetrievalAUROC, np_auroc, {}),
]


@pytest.mark.parametrize("cls,ref,kwargs", CLASS_CASES)
@pytest.mark.parametrize("empty_action", ["neg", "pos", "skip"])
def test_class_metrics(cls, ref, kwargs, empty_action):
    rng = np.random.default_rng(SEED)
    graded = cls is RetrievalNormalizedDCG
    queries = _query_data(rng, graded=graded)
    preds, target, indexes = _flat(queries)

    metric = cls(empty_target_action=empty_action, **kwargs)
    # feed in two chunks to exercise accumulation
    half = len(preds) // 2
    metric.update(jnp.asarray(preds[:half]), jnp.asarray(target[:half]), jnp.asarray(indexes[:half]))
    metric.update(jnp.asarray(preds[half:]), jnp.asarray(target[half:]), jnp.asarray(indexes[half:]))
    got = float(metric.compute())

    ref_kwargs = {k: v for k, v in kwargs.items()}
    scores = []
    for p, t in queries:
        if t.sum() == 0:
            if empty_action == "skip":
                continue
            scores.append(1.0 if empty_action == "pos" else 0.0)
        else:
            scores.append(float(ref(p, t, **ref_kwargs)))
    want = float(np.mean(scores)) if scores else 0.0
    assert got == pytest.approx(want, abs=1e-5)


def test_fall_out_class():
    rng = np.random.default_rng(SEED)
    queries = _query_data(rng, empty_frac=0.0)
    preds, target, indexes = _flat(queries)
    metric = RetrievalFallOut(top_k=3)
    metric.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    got = float(metric.compute())
    scores = []
    for p, t in queries:
        if (1 - t).sum() == 0:
            scores.append(0.0)
        else:
            scores.append(np_fall_out(p, t, top_k=3))
    assert got == pytest.approx(float(np.mean(scores)), abs=1e-5)


def test_pr_curve_class_and_recall_at_precision():
    rng = np.random.default_rng(SEED)
    queries = _query_data(rng, empty_frac=0.0, min_docs=6, max_docs=10)
    preds, target, indexes = _flat(queries)

    max_k = 5
    metric = RetrievalPrecisionRecallCurve(max_k=max_k)
    metric.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    prec, rec, topk = metric.compute()

    ps, rs = [], []
    for p, t in queries:
        rp, rr = np_pr_curve(p, t, max_k)
        ps.append(rp)
        rs.append(rr)
    np.testing.assert_allclose(np.asarray(prec), np.mean(ps, axis=0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(rec), np.mean(rs, axis=0), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(topk), np.arange(1, max_k + 1))

    # recall at fixed precision: brute-force over the averaged curve
    m2 = RetrievalRecallAtFixedPrecision(min_precision=0.4, max_k=max_k)
    m2.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    best_r, best_k = m2.compute()
    avg_p, avg_r = np.mean(ps, axis=0), np.mean(rs, axis=0)
    cands = [(r, k) for p_, r, k in zip(avg_p, avg_r, range(1, max_k + 1)) if p_ >= 0.4]
    want_r, want_k = max(cands) if cands else (0.0, max_k)
    assert float(best_r) == pytest.approx(want_r, abs=1e-5)
    assert int(best_k) == want_k


def test_auroc_tie_half_credit():
    # tied pos/neg score pairs must get 0.5 credit, not win/lose by sort order
    assert float(retrieval_auroc(jnp.asarray([0.5, 0.5]), jnp.asarray([1, 0]))) == pytest.approx(0.5)
    assert float(retrieval_auroc(jnp.asarray([0.5, 0.5]), jnp.asarray([0, 1]))) == pytest.approx(0.5)
    p = np.array([0.9, 0.5, 0.5, 0.5, 0.1])
    t = np.array([1, 1, 0, 0, 1])
    assert float(retrieval_auroc(jnp.asarray(p), jnp.asarray(t))) == pytest.approx(np_auroc(p, t))


def test_functional_rejects_graded_target():
    with pytest.raises(ValueError, match="binary"):
        retrieval_precision(jnp.asarray([0.9, 0.1]), jnp.asarray([2, 0]))


def test_pr_curve_compute_before_update():
    prec, rec, topk = RetrievalPrecisionRecallCurve(max_k=3).compute()
    np.testing.assert_array_equal(np.asarray(prec), np.zeros(3))
    np.testing.assert_array_equal(np.asarray(topk), [1, 2, 3])


def test_aggregation_modes():
    rng = np.random.default_rng(SEED)
    queries = _query_data(rng, empty_frac=0.0)
    preds, target, indexes = _flat(queries)
    scores = [np_precision(p, t, top_k=2) for p, t in queries]
    for agg, ref in [("median", np.median), ("min", np.min), ("max", np.max)]:
        m = RetrievalPrecision(top_k=2, aggregation=agg)
        m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
        assert float(m.compute()) == pytest.approx(float(ref(scores)), abs=1e-5)


def test_empty_target_error_raises():
    m = RetrievalMAP(empty_target_action="error")
    m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 0]), jnp.asarray([0, 0]))
    with pytest.raises(ValueError, match="no positive target"):
        m.compute()


def test_ignore_index():
    m = RetrievalMAP(ignore_index=-1)
    preds = jnp.asarray([0.9, 0.2, 0.5, 0.3])
    target = jnp.asarray([1, -1, 0, 1])
    idx = jnp.asarray([0, 0, 0, 0])
    m.update(preds, target, idx)
    want = np_average_precision(np.array([0.9, 0.5, 0.3]), np.array([1, 0, 1]))
    assert float(m.compute()) == pytest.approx(want, abs=1e-5)


def test_non_binary_raises():
    m = RetrievalMAP()
    with pytest.raises(ValueError, match="binary"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([0, 2]), jnp.asarray([0, 0]))


def test_merge_and_reset():
    rng = np.random.default_rng(SEED)
    queries = _query_data(rng, empty_frac=0.0)
    preds, target, indexes = _flat(queries)
    m = RetrievalMAP()
    s1 = m.update_state(m.init_state(), jnp.asarray(preds[:10]), jnp.asarray(target[:10]), jnp.asarray(indexes[:10]))
    s2 = m.update_state(m.init_state(), jnp.asarray(preds[10:]), jnp.asarray(target[10:]), jnp.asarray(indexes[10:]))
    merged = m.merge_states(s1, s2)
    full = m.update_state(m.init_state(), jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    assert float(m.compute_state(merged)) == pytest.approx(float(m.compute_state(full)), abs=1e-6)
    m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    m.reset()
    assert m.metric_state["preds"] == ()
