"""utilities.benchmark: jitted metric micro-benchmark helper."""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.utilities import benchmark


def test_benchmark_reports_timings_and_state():
    from torchmetrics_tpu.classification import MulticlassAccuracy

    m = MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    probs = jnp.asarray(np.random.default_rng(0).uniform(size=(16, 5)), jnp.float32)
    target = jnp.asarray(np.random.default_rng(1).integers(0, 5, 16))
    rep = benchmark(m, probs, target, steps=10, n_devices=8)
    assert rep["metric"] == "MulticlassAccuracy"
    assert rep["update_us"] > 0 and rep["compute_us"] > 0
    assert rep["state_bytes"] > 0 and rep["state_leaves"] >= 1
    assert rep["sync_bytes_per_chip"] > 0


def test_benchmark_rejects_list_state_metrics():
    from torchmetrics_tpu.regression import SpearmanCorrCoef

    with pytest.raises(ValueError, match="cat"):
        benchmark(SpearmanCorrCoef(), jnp.zeros(4), jnp.zeros(4))
