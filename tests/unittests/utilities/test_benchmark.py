"""utilities.benchmark: jitted metric micro-benchmark helper."""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.utilities import benchmark


def test_benchmark_reports_timings_and_state():
    from torchmetrics_tpu.classification import MulticlassAccuracy

    m = MulticlassAccuracy(num_classes=5, average="micro", validate_args=False)
    probs = jnp.asarray(np.random.default_rng(0).uniform(size=(16, 5)), jnp.float32)
    target = jnp.asarray(np.random.default_rng(1).integers(0, 5, 16))
    rep = benchmark(m, probs, target, steps=10, n_devices=8)
    assert rep["metric"] == "MulticlassAccuracy"
    assert rep["update_us"] > 0 and rep["compute_us"] > 0
    assert rep["state_bytes"] > 0 and rep["state_leaves"] >= 1
    assert rep["sync_bytes_per_chip"] > 0


def test_benchmark_rejects_list_state_metrics():
    from torchmetrics_tpu.regression import SpearmanCorrCoef

    with pytest.raises(ValueError, match="cat"):
        benchmark(SpearmanCorrCoef(), jnp.zeros(4), jnp.zeros(4))


# ----------------------------------------------------- compressed byte models
def test_sync_wire_bytes_models_compression():
    from torchmetrics_tpu.parallel.compress import CompressionConfig
    from torchmetrics_tpu.utilities.benchmark import (
        coalesced_sync_bytes_per_chip,
        sync_bytes_per_chip,
        sync_wire_bytes_per_chip,
    )

    table = {"s": "sum"}
    state = {"s": np.zeros((4096,), np.float32), "_n": np.ones((), np.int32)}
    exact = sync_wire_bytes_per_chip(table, state, 8, None)
    bf16 = sync_wire_bytes_per_chip(table, state, 8, CompressionConfig("bf16"))
    int8 = sync_wire_bytes_per_chip(table, state, 8, CompressionConfig("int8"))
    assert bf16 < exact and int8 < bf16
    assert exact / int8 >= 2.0
    # the ring-granule model orders the same way
    r_exact = coalesced_sync_bytes_per_chip(table, state, 8)
    r_int8 = coalesced_sync_bytes_per_chip(table, state, 8, compression=CompressionConfig("int8"))
    assert r_int8 < r_exact
    # exact wire model stays consistent with the legacy per-chip model's scale
    legacy = sync_bytes_per_chip(table, state, 8)
    assert exact == pytest.approx(legacy, rel=0.05)


def test_two_stage_dcn_bytes_compression():
    from torchmetrics_tpu.parallel.compress import CompressionConfig
    from torchmetrics_tpu.utilities.benchmark import two_stage_dcn_bytes

    table = {"s": "sum"}
    state = {"s": np.zeros((8192,), np.float32), "_n": np.ones((), np.int32)}
    exact = two_stage_dcn_bytes(table, state, n_hosts=4, n_local_devices=8)
    bf16 = two_stage_dcn_bytes(
        table, state, n_hosts=4, n_local_devices=8, compression=CompressionConfig("bf16")
    )
    int8 = two_stage_dcn_bytes(
        table, state, n_hosts=4, n_local_devices=8, compression=CompressionConfig("int8")
    )
    for key in exact:
        assert bf16[key] <= exact[key], key
        assert int8[key] <= exact[key], key
    assert bf16 != exact and int8 != exact


def test_small_buckets_never_compressed_in_models():
    from torchmetrics_tpu.parallel.compress import CompressionConfig
    from torchmetrics_tpu.utilities.benchmark import sync_wire_bytes_per_chip

    table = {"s": "sum"}
    state = {"s": np.zeros((16,), np.float32), "_n": np.ones((), np.int32)}
    cfg = CompressionConfig("int8")
    assert sync_wire_bytes_per_chip(table, state, 8, cfg) == sync_wire_bytes_per_chip(
        table, state, 8, None
    )
