"""Bench regression tracker: archive parsing (clean, crashed, and truncated
records), direction-aware noise bands, device gating, and the
``bench.py --check-regressions`` front door."""

import json

import pytest

from torchmetrics_tpu.utilities.regression import (
    BenchRun,
    RegressionTracker,
    band_for,
    check_regressions,
    direction_for,
    flatten_numeric,
    load_bench_history,
    recover_numeric_pairs,
)


def _archive(tmp_path, n, parsed=None, rc=0, tail=""):
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"n": n, "cmd": "python bench.py", "rc": rc,
                                "tail": tail, "parsed": parsed}))
    return path


def _record(value, device="cpu", **detail):
    detail.setdefault("device", device)
    return {"metric": "overhead", "value": value, "unit": "%", "detail": detail}


# ------------------------------------------------------------------- parsing
def test_flatten_numeric_dotted_keys_and_bool_exclusion():
    flat = flatten_numeric({"a": 1, "b": {"c": 2.5, "ok": True}, "d": [3, "x"]})
    assert flat == {"a": 1.0, "b.c": 2.5, "d.0": 3.0}


def test_recover_numeric_pairs_drops_ambiguous_keys():
    tail = '"x": 1.5, "dup": 2, "y": -3e-2, "dup": 7'
    pairs = recover_numeric_pairs(tail)
    assert pairs == {"x": 1.5, "y": -0.03}


def test_load_history_handles_all_archive_shapes(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.5, device="tpu"))
    _archive(tmp_path, 2, rc=1, tail="Traceback (most recent call last): boom")
    # truncated tail: starts mid-object, parsed is null (the BENCH_r05 shape)
    _archive(tmp_path, 3, tail='0.3, "train_step_ms_median": 42.0, "device": "cpu"')
    _archive(tmp_path, 4, tail="no numbers here at all")
    runs = load_bench_history(str(tmp_path))
    assert [r.n for r in runs] == [1, 3]
    assert runs[0].device == "tpu" and not runs[0].partial
    assert runs[1].device == "cpu" and runs[1].partial
    assert runs[1].values["train_step_ms_median"] == 42.0


def test_partial_run_lookup_matches_dotted_suffix():
    run = BenchRun(n=1, rc=0, source="r", values={"train_step_ms_median": 42.0})
    assert run.lookup("detail.train_step_ms_median") == 42.0
    assert run.lookup("detail.absent") is None


# ---------------------------------------------------------- directions & bands
def test_direction_heuristics():
    assert direction_for("detail.metric_subgraph_us_per_step") == "lower"
    assert direction_for("detail.sync_bytes") == "lower"
    assert direction_for("detail.overhead_pct_trimmed_mean") == "lower"
    assert direction_for("detail.sync_time_cut_every_4") == "higher"
    assert direction_for("detail.fused_speedup") == "higher"
    assert direction_for("detail.num_classes") is None  # descriptive


def test_band_classes():
    assert band_for("detail.train_step_ms_median") >= 0.60  # wall clock: wide
    assert band_for("detail.psum_state_bytes") == 0.01  # analytic: tight
    assert band_for("detail.overhead_pct_trimmed_mean") == 0.30


# ------------------------------------------------------------------ the gate
def test_unchanged_run_passes(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.8, step_ms=50.0, psum_state_bytes=1024))
    rep = check_regressions(
        _record(0.8, step_ms=50.0, psum_state_bytes=1024), history_dir=str(tmp_path)
    )
    assert rep.verdict == "pass" and not rep.failures


def test_analytic_regression_fails_tight(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.8, psum_state_bytes=1024))
    rep = check_regressions(
        _record(0.8, psum_state_bytes=1100), history_dir=str(tmp_path)
    )
    assert rep.verdict == "fail"
    assert [c.key for c in rep.failures] == ["detail.psum_state_bytes"]


def test_timing_noise_within_band_passes(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.8, step_ms=50.0))
    rep = check_regressions(_record(0.8, step_ms=70.0), history_dir=str(tmp_path))
    assert rep.verdict == "pass"  # +40% < the 60% wall-clock band


def test_higher_better_gates_decreases(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.8, sync_time_cut_every_4=5.0))
    bad = check_regressions(
        _record(0.8, sync_time_cut_every_4=1.1), history_dir=str(tmp_path)
    )
    assert [c.key for c in bad.failures] == ["detail.sync_time_cut_every_4"]
    ok = check_regressions(
        _record(0.8, sync_time_cut_every_4=9.0), history_dir=str(tmp_path)
    )
    assert ok.verdict == "pass"


def test_band_widens_to_historical_spread(tmp_path):
    # history itself disagrees 4x on a wall-clock leg: a current value inside
    # that measured spread must not fail
    _archive(tmp_path, 1, parsed=_record(0.8, step_ms=200.0))
    _archive(tmp_path, 2, parsed=_record(0.8, step_ms=50.0))
    rep = check_regressions(_record(0.8, step_ms=190.0), history_dir=str(tmp_path))
    assert rep.verdict == "pass"


def test_negative_baseline_uses_additive_band(tmp_path):
    # sign-flipping noise stats: baseline -0.05, current +0.2 is within any
    # sane band and must not fail on a multiplicative-threshold inversion
    _archive(tmp_path, 1, parsed=_record(0.8, overhead_pct_raw_mean=-0.05))
    rep = check_regressions(
        _record(0.8, overhead_pct_raw_mean=0.2), history_dir=str(tmp_path)
    )
    assert rep.verdict == "pass"


def test_device_mismatch_never_cross_gates(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.1, device="tpu", step_ms=2.0))
    rep = check_regressions(
        _record(5.0, device="cpu", step_ms=900.0), history_dir=str(tmp_path)
    )
    assert rep.verdict == "no-baseline"
    assert rep.skipped_device_mismatch > 0


def test_no_history_is_no_baseline(tmp_path):
    rep = check_regressions(_record(0.8), history_dir=str(tmp_path))
    assert rep.verdict == "no-baseline" and rep.comparisons == []


# ------------------------------------------------------------------- reporting
def test_markdown_and_dict_shapes(tmp_path):
    _archive(tmp_path, 1, parsed=_record(0.8, psum_state_bytes=1024, num_classes=5))
    rep = check_regressions(
        _record(0.8, psum_state_bytes=4096, num_classes=5), history_dir=str(tmp_path)
    )
    md = rep.to_markdown()
    assert "## Bench regression check" in md
    assert "**Verdict: FAIL**" in md
    assert "`detail.psum_state_bytes`" in md and "fail" in md
    d = rep.to_dict()
    assert d["metric"] == "bench-regression-check"
    assert d["verdict"] == "fail" and d["n_failures"] == 1
    assert d["failures"][0]["key"] == "detail.psum_state_bytes"
    json.dumps(d)  # machine-readable: must serialize


# ------------------------------------------------------- bench.py front door
def _load_bench_module():
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[3]
    spec = importlib.util.spec_from_file_location("_bench_cli", root / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_check_regressions_cli(tmp_path, monkeypatch):
    import sys

    bench = _load_bench_module()
    _archive(tmp_path, 1, parsed=_record(0.8, psum_state_bytes=1024))
    current = tmp_path / "current.json"
    current.write_text(json.dumps(_record(0.8, psum_state_bytes=1024)))
    monkeypatch.setenv("BENCH_HISTORY_DIR", str(tmp_path))
    monkeypatch.setattr(
        sys, "argv", ["bench.py", "--check-regressions", "--input", str(current)]
    )
    with pytest.raises(SystemExit) as exc:
        bench.check_regressions_cli()
    assert exc.value.code == 0

    current.write_text(json.dumps(_record(0.8, psum_state_bytes=9999)))
    with pytest.raises(SystemExit) as exc:
        bench.check_regressions_cli()
    assert exc.value.code == 3  # regression exit code, distinct from crash


def test_bench_cli_emits_machine_readable_verdict(tmp_path, monkeypatch, capsys):
    import sys

    bench = _load_bench_module()
    _archive(tmp_path, 1, parsed=_record(0.8, psum_state_bytes=1024))
    current = tmp_path / "current.json"
    current.write_text(json.dumps(_record(0.8, psum_state_bytes=1024)))
    monkeypatch.setenv("BENCH_HISTORY_DIR", str(tmp_path))
    monkeypatch.setattr(
        sys, "argv", ["bench.py", "--check-regressions", "--input", str(current)]
    )
    with pytest.raises(SystemExit):
        bench.check_regressions_cli()
    out = capsys.readouterr()
    verdict = json.loads(out.out.strip().splitlines()[-1])
    assert verdict["metric"] == "bench-regression-check"
    assert verdict["verdict"] == "pass"
    assert "## Bench regression check" in out.err


def test_bytes_keys_gate_lower_is_better():
    """Satellite: every *_bytes bench key — including bare ``bytes`` /
    ``bytes_per_chip`` leaves from the compressed-sync leg — is a
    lower-is-better analytic quantity, while realized cut ratios stay
    higher-is-better."""
    for key in (
        "detail.compressed_sync.byte_model.int8_bytes_per_chip",
        "detail.compressed_sync.bitpacked_ragged_gather.wire_bytes_packed",
        "detail.sync_bytes_raw",
        "detail.telemetry_vs_model.sync_bytes_counter",
        "detail.bucket.bytes",
    ):
        assert direction_for(key) == "lower", key
        assert band_for(key) == 0.01, key  # analytic: tight band
    assert direction_for("detail.compressed_sync.byte_model.int8_byte_cut") == "higher"
    assert direction_for("detail.bf16_byte_cut") == "higher"


def test_autotune_keys_gate_lower_is_better():
    """Satellite: the autotune leg's sync wall times gate lower-is-better in
    the wide timing band, while its transition retrace counters are analytic
    lower-is-better quantities in the tight band."""
    for key in (
        "detail.autotune.sync_time.naive_sync_s",
        "detail.autotune.sync_time.hand_tuned_sync_s",
        "detail.autotune.sync_time.autotuned_sync_s",
    ):
        assert direction_for(key) == "lower", key
        assert band_for(key) >= 0.60, key  # wall clock: wide
    for key in (
        "detail.autotune.transition_retraces.extra_retraces",
        "detail.autotune.transition_retraces.extra_misses",
        "detail.autotune.compression_transition.extra_misses",
    ):
        assert direction_for(key) == "lower", key
        assert band_for(key) == 0.01, key  # analytic: tight
    # convergence ratio and ledger/export smoke counts: higher is better
    assert direction_for("detail.autotune.sync_time.naive_over_autotuned_cut") == "higher"
    assert direction_for("detail.autotune.observability.prometheus_lines") == "higher"
