"""Property tests: classify_inputs vs the reference's input-format layer.

For every input case of the decision table x a grid of (top_k, num_classes,
multiclass) parameters, randomized inputs must either (a) be accepted by
both implementations with the SAME case and the SAME canonical tensors, or
(b) be rejected by both.  This is the parity contract VERDICT r3 #9 asks
for against /root/reference/src/torchmetrics/utilities/checks.py:207,315.
"""

import numpy as np
import pytest

from tests.helpers.refpath import require_reference

require_reference()

import torch  # noqa: E402

from torchmetrics_tpu.utilities.formatting import classify_inputs  # noqa: E402

N = 12
C = 4
X = 3


def _ref_format(preds, target, **kw):
    from torchmetrics.utilities.checks import _input_format_classification

    return _input_format_classification(torch.tensor(preds), torch.tensor(target), **kw)


def _gen(case, rng):
    if case == "binary_probs":
        return rng.uniform(size=N).astype(np.float32), rng.integers(0, 2, N)
    if case == "mc_labels":
        return rng.integers(0, C, N), rng.integers(0, C, N)
    if case == "mc_probs":
        logits = rng.normal(size=(N, C)).astype(np.float32)
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        return probs, rng.integers(0, C, N)
    if case == "multilabel":
        return rng.uniform(size=(N, C)).astype(np.float32), rng.integers(0, 2, (N, C))
    if case == "mdmc_probs":
        logits = rng.normal(size=(N, C, X)).astype(np.float32)
        probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        return probs, rng.integers(0, C, (N, X))
    if case == "mdmc_labels":
        return rng.integers(0, C, (N, X)), rng.integers(0, C, (N, X))
    raise AssertionError(case)


CASES = ["binary_probs", "mc_labels", "mc_probs", "multilabel", "mdmc_probs", "mdmc_labels"]
PARAM_GRID = [
    {},
    {"top_k": 2},
    {"num_classes": C},
    {"multiclass": True},
    {"multiclass": False},
    {"top_k": 2, "num_classes": C},
    {"num_classes": 2, "multiclass": True},
]


@pytest.mark.parametrize("case", CASES)
@pytest.mark.parametrize("params", PARAM_GRID, ids=[str(p) for p in PARAM_GRID])
def test_classify_inputs_reference_parity(case, params):
    import zlib

    rng = np.random.default_rng(zlib.crc32(case.encode()))
    for _ in range(3):
        preds, target = _gen(case, rng)

        ref_err = ours_err = None
        try:
            ref_p, ref_t, ref_case = _ref_format(preds, target, **params)
        except (ValueError, RuntimeError) as err:
            ref_err = err
        try:
            our_p, our_t, our_case = classify_inputs(preds, target, **params)
        except (ValueError, RuntimeError) as err:
            ours_err = err

        if ref_err is not None or ours_err is not None:
            assert ref_err is not None and ours_err is not None, (
                f"accept/reject divergence for {case} {params}: ref={ref_err}, ours={ours_err}"
            )
            continue

        assert our_case.value == ref_case.value, f"{case} {params}: case mismatch"
        np.testing.assert_array_equal(
            np.asarray(our_p), ref_p.numpy(), err_msg=f"{case} {params}: preds mismatch"
        )
        np.testing.assert_array_equal(
            np.asarray(our_t), ref_t.numpy(), err_msg=f"{case} {params}: target mismatch"
        )


def test_classify_inputs_squeeze_and_extra_dims():
    """Size-1 dims (except batch) are squeezed before classification."""
    rng = np.random.default_rng(0)
    probs = rng.uniform(size=(N, 1, C, 1)).astype(np.float32)
    target = rng.integers(0, C, (N, 1))
    ref = _ref_format(probs, target)
    ours = classify_inputs(probs, target)
    assert ours[2].value == ref[2].value
    np.testing.assert_array_equal(np.asarray(ours[0]), ref[0].numpy())


def test_classify_inputs_rejects_mismatched_shapes():
    with pytest.raises(ValueError):
        classify_inputs(np.zeros((4, 3), np.float32), np.zeros((5,), np.int64))
    with pytest.raises(ValueError):
        classify_inputs(np.zeros((4, 3, 2), np.int64), np.zeros((4,), np.int64))
    with pytest.raises(ValueError):
        classify_inputs(np.zeros((4,), np.float32), np.zeros((4,), np.float32))  # float target


def test_classify_inputs_bfloat16_probs():
    """bfloat16 probabilities — the native TPU dtype — must classify as
    float probabilities, not integer labels."""
    import jax.numpy as jnp

    probs = jnp.asarray([0.2, 0.7, 0.9, 0.1], jnp.bfloat16)
    target = np.asarray([0, 1, 1, 0])
    p, t, case = classify_inputs(probs, target)
    assert case.value == "binary"
    np.testing.assert_array_equal(np.asarray(p).ravel(), [0, 1, 1, 0])


def test_classify_inputs_out_of_range_int_preds_raise():
    """Integer preds >= num_classes must raise (the reference rejects via
    its scatter; a silent zero one-hot row would corrupt downstream stats)."""
    with pytest.raises(ValueError, match="preds"):
        classify_inputs(np.asarray([5, 0]), np.asarray([0, 1]), num_classes=4)


def test_classify_inputs_ignore_index_zero_quirk():
    """ignore_index=0 disables the target-negativity check exactly like the
    reference's falsy-zero condition (checks.py:62); ignore_index=1 keeps it."""
    preds = np.asarray([0.5, 0.6], np.float32)
    ref = _ref_format(preds, np.asarray([-1, 1]), ignore_index=0)
    ours = classify_inputs(preds, np.asarray([-1, 1]), ignore_index=0)
    assert ours[2].value == ref[2].value
    with pytest.raises(ValueError):
        classify_inputs(preds, np.asarray([-1, 1]), ignore_index=1)
