"""Clustering metrics vs sklearn references.

Mirrors the reference test strategy (tests/unittests/clustering/*) — sklearn
is the ground truth, batch accumulation must match single-shot compute.
"""

import numpy as np
import pytest
from sklearn.metrics import (
    adjusted_mutual_info_score as sk_ami,
    adjusted_rand_score as sk_ari,
    calinski_harabasz_score as sk_ch,
    completeness_score as sk_completeness,
    davies_bouldin_score as sk_db,
    fowlkes_mallows_score as sk_fm,
    homogeneity_score as sk_homogeneity,
    mutual_info_score as sk_mi,
    normalized_mutual_info_score as sk_nmi,
    rand_score as sk_rand,
    v_measure_score as sk_v,
)

from torchmetrics_tpu.clustering import (
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)
from torchmetrics_tpu.functional.clustering import (
    adjusted_mutual_info_score,
    dunn_index,
    mutual_info_score,
)

N = 128
K = 5


def _labels(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, K, size=N), rng.randint(0, K, size=N)


EXTRINSIC_CASES = [
    (MutualInfoScore, {}, sk_mi),
    (AdjustedMutualInfoScore, {}, sk_ami),
    (NormalizedMutualInfoScore, {}, sk_nmi),
    (RandScore, {}, sk_rand),
    (AdjustedRandScore, {}, sk_ari),
    (FowlkesMallowsIndex, {}, sk_fm),
    (HomogeneityScore, {}, sk_homogeneity),
    (CompletenessScore, {}, sk_completeness),
    (VMeasureScore, {}, sk_v),
]


@pytest.mark.parametrize("cls,kwargs,sk_fn", EXTRINSIC_CASES)
def test_extrinsic_vs_sklearn(cls, kwargs, sk_fn):
    preds, target = _labels()
    metric = cls(**kwargs)
    # batched accumulation
    for i in range(0, N, 32):
        metric.update(preds[i : i + 32], target[i : i + 32])
    # sklearn signature is (labels_true, labels_pred)
    expected = sk_fn(target, preds)
    assert np.allclose(float(metric.compute()), expected, atol=1e-5), cls.__name__


@pytest.mark.parametrize(
    "average_method", ["min", "geometric", "arithmetic", "max"]
)
def test_ami_nmi_average_methods(average_method):
    preds, target = _labels(3)
    ami = AdjustedMutualInfoScore(average_method=average_method)
    ami.update(preds, target)
    assert np.allclose(
        float(ami.compute()), sk_ami(target, preds, average_method=average_method), atol=1e-5
    )
    nmi = NormalizedMutualInfoScore(average_method=average_method)
    nmi.update(preds, target)
    assert np.allclose(
        float(nmi.compute()), sk_nmi(target, preds, average_method=average_method), atol=1e-5
    )


def test_perfect_and_independent():
    x = np.arange(64) % 4
    m = AdjustedRandScore()
    m.update(x, x)
    assert np.allclose(float(m.compute()), 1.0)
    f = NormalizedMutualInfoScore()
    f.update(x, x)
    assert np.allclose(float(f.compute()), 1.0, atol=1e-6)


@pytest.mark.parametrize("cls,sk_fn", [(CalinskiHarabaszScore, sk_ch), (DaviesBouldinScore, sk_db)])
def test_intrinsic_vs_sklearn(cls, sk_fn):
    rng = np.random.RandomState(7)
    data = rng.randn(N, 8).astype(np.float32)
    labels = rng.randint(0, 4, size=N)
    metric = cls()
    for i in range(0, N, 32):
        metric.update(data[i : i + 32], labels[i : i + 32])
    assert np.allclose(float(metric.compute()), sk_fn(data, labels), rtol=1e-4), cls.__name__


def test_dunn_index_reference_example():
    # hand-checkable example from the reference docstring
    # (functional/clustering/dunn_index.py:75-79)
    data = np.array([[0.0, 0.0], [0.5, 0.0], [1.0, 0.0], [0.5, 1.0]])
    labels = np.array([0, 0, 0, 1])
    assert np.allclose(float(dunn_index(data, labels)), 2.0, atol=1e-6)
    m = DunnIndex(p=2)
    m.update(data, labels)
    assert np.allclose(float(m.compute()), 2.0, atol=1e-6)


def test_functional_matches_modular():
    preds, target = _labels(11)
    assert np.allclose(
        float(mutual_info_score(preds, target)),
        float(adjusted_mutual_info_score(preds, target)) * 0 + sk_mi(target, preds),
        atol=1e-5,
    )


def test_merge_states_equals_single_shot():
    preds, target = _labels(5)
    a = MutualInfoScore()
    b = MutualInfoScore()
    a.update(preds[:64], target[:64])
    b.update(preds[64:], target[64:])
    merged = a.merge_states(a.metric_state, b.metric_state)
    full = MutualInfoScore()
    full.update(preds, target)
    assert np.allclose(float(a.compute_state(merged)), sk_mi(target, preds), atol=1e-5)
