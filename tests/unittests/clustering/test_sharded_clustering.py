"""Clustering metrics through the 8-device sharded-sync path.

Enrollment of the universal sharded tester for the clustering domain
(VERDICT r4 next #2).  Every clustering state is a cat state (label or data
rows accumulate; compute is global) — sharding splits the rows of the SAME
clustering across devices, so the tiled all_gather leg is what makes the
contingency/ scatter computations come out right.
"""

import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity

N = 64  # points per step; 8 devices x 8


@pytest.fixture()
def label_pairs():
    rng = np.random.default_rng(31)
    preds = rng.integers(0, 4, size=(2, N))
    target = rng.integers(0, 3, size=(2, N))
    return preds, target


def _batches(*arrays):
    return [tuple(a[0] for a in arrays), tuple(a[1] for a in arrays)]


@pytest.mark.parametrize(
    "name,sk_name",
    [
        ("MutualInfoScore", "mutual_info_score"),
        ("AdjustedRandScore", "adjusted_rand_score"),
        ("NormalizedMutualInfoScore", "normalized_mutual_info_score"),
        ("VMeasureScore", "v_measure_score"),
        ("FowlkesMallowsIndex", "fowlkes_mallows_score"),
        ("HomogeneityScore", "homogeneity_score"),
        ("CompletenessScore", "completeness_score"),
    ],
)
def test_sharded_extrinsic_clustering(mesh, label_pairs, name, sk_name):
    sk = pytest.importorskip("sklearn.metrics")
    import torchmetrics_tpu.clustering as C

    preds, target = label_pairs
    oracle = float(getattr(sk, sk_name)(target.ravel(), preds.ravel()))
    assert_sharded_parity(
        mesh, getattr(C, name), _batches(preds, target), oracle=oracle, atol=1e-5
    )


@pytest.mark.parametrize(
    "name,sk_name",
    [
        ("CalinskiHarabaszScore", "calinski_harabasz_score"),
        ("DaviesBouldinScore", "davies_bouldin_score"),
    ],
)
def test_sharded_intrinsic_clustering(mesh, name, sk_name):
    sk = pytest.importorskip("sklearn.metrics")
    import torchmetrics_tpu.clustering as C

    rng = np.random.default_rng(32)
    data = rng.normal(size=(2, N, 5)).astype(np.float32)
    labels = rng.integers(0, 3, size=(2, N))
    oracle = float(
        getattr(sk, sk_name)(data.reshape(-1, 5), labels.ravel())
    )
    assert_sharded_parity(
        mesh, getattr(C, name), _batches(data, labels), oracle=oracle, atol=1e-4, rtol=1e-4
    )
