"""Cross-implementation parity: our metrics vs the ACTUAL reference.

The reference (/root/reference/src/torchmetrics) is imported directly — only a
~100-line ``lightning_utilities`` stub (tests/helpers/stubs) is needed; torch
(CPU) is installed.  MetricTester-style protocol (reference
tests/unittests/_helpers/testers.py:74-228): identical inputs are fed
batch-by-batch to both implementations and the accumulated ``compute()``
results must agree.  This anchors ~90 metrics to the reference itself rather
than to oracles re-derived in our own test files (VERDICT r1 "next" #4).
"""

from __future__ import annotations


import numpy as np
import pytest

from tests.helpers.refpath import require_reference

require_reference()

import jax.numpy as jnp  # noqa: E402
import torch  # noqa: E402

import torchmetrics as R  # noqa: E402  (the reference)
import torchmetrics_tpu as T  # noqa: E402  (ours)

N = 32
N_BATCHES = 4
C = 5
L = 4
SEED = 1234


# ------------------------------------------------------------------ plumbing
def _to_numpy(x):
    if isinstance(x, torch.Tensor):
        return x.detach().cpu().numpy()
    if isinstance(x, (jnp.ndarray, np.ndarray)):
        return np.asarray(x)
    return x


def _assert_close(ours, ref, atol, path=""):
    if isinstance(ref, dict):
        assert isinstance(ours, dict), f"{path}: ours={type(ours)}"
        for k in ref:
            assert k in ours, f"{path}: missing key {k} (have {list(ours)})"
            _assert_close(ours[k], ref[k], atol, f"{path}.{k}")
        return
    if isinstance(ref, (list, tuple)) and not isinstance(ref, torch.Tensor):
        assert len(ours) == len(ref), f"{path}: len {len(ours)} != {len(ref)}"
        for i, (a, b) in enumerate(zip(ours, ref)):
            _assert_close(a, b, atol, f"{path}[{i}]")
        return
    a, b = _to_numpy(ours), _to_numpy(ref)
    if isinstance(b, (int, float)) or (hasattr(b, "ndim") and b.ndim == 0):
        a, b = np.asarray(a, dtype=np.float64), np.asarray(b, dtype=np.float64)
    np.testing.assert_allclose(
        np.asarray(a, dtype=np.float64),
        np.asarray(b, dtype=np.float64),
        atol=atol,
        rtol=1e-4,
        err_msg=f"mismatch at {path}",
    )


def _as_jax(x):
    if isinstance(x, np.ndarray):
        return jnp.asarray(x)
    return x


def _as_torch(x):
    if isinstance(x, np.ndarray):
        return torch.as_tensor(x)
    return x


class Case:
    def __init__(self, cid, ours, ref, gen, atol=1e-5, kwargs_keys=(), ref_post=None):
        self.id = cid
        self.ours = ours
        self.ref = ref
        self.gen = gen
        self.atol = atol
        self.kwargs_keys = kwargs_keys
        self.ref_post = ref_post


# --------------------------------------------------------------- input gens
def g_binary(rng, i):
    return rng.random(N).astype(np.float32), rng.integers(0, 2, N)


def g_multiclass(rng, i):
    lg = rng.standard_normal((N, C)).astype(np.float32)
    p = np.exp(lg) / np.exp(lg).sum(1, keepdims=True)
    return p, rng.integers(0, C, N)


def g_multilabel(rng, i):
    return rng.random((N, L)).astype(np.float32), rng.integers(0, 2, (N, L))


def g_regression(rng, i):
    return rng.standard_normal(N).astype(np.float32), rng.standard_normal(N).astype(np.float32)


def g_regression_pos(rng, i):
    return (rng.random(N).astype(np.float32) + 0.1), (rng.random(N).astype(np.float32) + 0.1)


def g_regression_2d(rng, i):
    return rng.standard_normal((N, 3)).astype(np.float32), rng.standard_normal((N, 3)).astype(np.float32)


def g_kldiv(rng, i):
    p = rng.random((N, C)).astype(np.float32) + 0.05
    q = rng.random((N, C)).astype(np.float32) + 0.05
    return p / p.sum(1, keepdims=True), q / q.sum(1, keepdims=True)


def g_scalar(rng, i):
    return (rng.standard_normal(N).astype(np.float32),)


def g_labels(rng, i):
    return rng.integers(0, C, N), rng.integers(0, C, N)


def g_intrinsic(rng, i):
    return rng.standard_normal((N, 3)).astype(np.float32), rng.integers(0, 3, N)


def g_ratings(rng, i):
    # (n_samples, n_categories) counts summing to a fixed rater count
    counts = np.zeros((N, 4), dtype=np.int64)
    for r in range(10):
        cat = rng.integers(0, 4, N)
        np.add.at(counts, (np.arange(N), cat), 1)
    return (counts,)


def g_retrieval(rng, i):
    idx = np.sort(rng.integers(0, 6, N))
    return rng.random(N).astype(np.float32), rng.integers(0, 2, N), idx


CORPUS_PRED = [
    "the cat is on the mat",
    "a quick brown fox jumps over the lazy dog",
    "hello world this is a test",
    "the weather today is sunny and bright",
    "metrics libraries compute many scores",
    "jax compiles programs for accelerators",
    "the answer to the question is forty two",
    "deep networks learn hierarchical features",
]
CORPUS_TGT = [
    ["there is a cat on the mat", "a cat lies on the mat"],
    ["the quick brown fox jumped over the lazy dog"],
    ["hello world it is a test", "hi world this is the test"],
    ["today the weather is sunny and clear"],
    ["metric libraries compute lots of scores"],
    ["jax compiles numerical programs for tpus"],
    ["the answer to this question is forty two"],
    ["deep neural networks learn hierarchical representations"],
]


def g_text_pair(rng, i):
    k = [int(x) for x in rng.integers(0, len(CORPUS_PRED), 2)]
    return [CORPUS_PRED[k[0]], CORPUS_PRED[k[1]]], [CORPUS_TGT[k[0]], CORPUS_TGT[k[1]]]


def g_text_single(rng, i):
    k = [int(x) for x in rng.integers(0, len(CORPUS_PRED), 2)]
    return [CORPUS_PRED[k[0]], CORPUS_PRED[k[1]]], [CORPUS_TGT[k[0]][0], CORPUS_TGT[k[1]][0]]


def g_perplexity(rng, i):
    lg = rng.standard_normal((2, 8, C)).astype(np.float32)
    p = np.exp(lg) / np.exp(lg).sum(-1, keepdims=True)
    return p, rng.integers(0, C, (2, 8))


def g_squad(rng, i):
    preds = [{"prediction_text": CORPUS_PRED[int(rng.integers(0, 8))], "id": f"q{i}_{j}"} for j in range(2)]
    target = [
        {"answers": {"answer_start": [0], "text": [CORPUS_TGT[int(rng.integers(0, 8))][0]]}, "id": p["id"]}
        for j, p in enumerate(preds)
    ]
    return preds, target


def g_image(rng, i):
    return rng.random((2, 3, 16, 16)).astype(np.float32), rng.random((2, 3, 16, 16)).astype(np.float32)


def g_image_single(rng, i):
    return (rng.random((2, 3, 16, 16)).astype(np.float32),)


def g_exact_match(rng, i):
    # (N, C, S) probs + (N, S) labels — the reference multidim layout
    lg = rng.standard_normal((2, C, 8)).astype(np.float32)
    p = np.exp(lg) / np.exp(lg).sum(1, keepdims=True)
    return p, rng.integers(0, C, (2, 8))


def g_audio(rng, i):
    return rng.standard_normal((2, 800)).astype(np.float32), rng.standard_normal((2, 800)).astype(np.float32)


def g_segmentation(rng, i):
    # one-hot (N, C, H, W) masks
    lbl_p = rng.integers(0, 3, (2, 8, 8))
    lbl_t = rng.integers(0, 3, (2, 8, 8))
    p = np.eye(3, dtype=np.int64)[lbl_p].transpose(0, 3, 1, 2)
    t = np.eye(3, dtype=np.int64)[lbl_t].transpose(0, 3, 1, 2)
    return p, t


# ------------------------------------------------------------------- cases
def _cls(name):
    """(ours_cls, ref_cls) by identical name."""
    return getattr(T, name, None) or _sub(T, name), getattr(R, name, None) or _sub(R, name)


def _sub(mod, name):
    import importlib

    for sub in ("classification", "regression", "aggregation", "text", "clustering",
                "nominal", "retrieval", "image", "audio", "segmentation", "wrappers"):
        try:
            m = importlib.import_module(f"{mod.__name__}.{sub}")
        except ImportError:
            continue
        if hasattr(m, name):
            return getattr(m, name)
    raise AttributeError(f"{mod.__name__}.{name}")


def P(name, gen, atol=1e-5, retrieval=False, ref_post=None, **ctor):
    """Build a Case where both sides share the class name and ctor kwargs."""
    def ours():
        return _cls(name)[0](**ctor)

    def ref():
        return _cls(name)[1](**ctor)

    cid = name + ("" if not ctor else "[" + ",".join(f"{k}={v}" for k, v in ctor.items()) + "]")
    return Case(cid, ours, ref, gen, atol=atol, kwargs_keys=("indexes",) if retrieval else (),
                ref_post=ref_post)


CASES = [
    # ---- classification: stat-scores tower
    P("BinaryAccuracy", g_binary),
    P("MulticlassAccuracy", g_multiclass, num_classes=C, average="macro"),
    P("MulticlassAccuracy", g_multiclass, num_classes=C, average="micro"),
    P("MultilabelAccuracy", g_multilabel, num_labels=L),
    P("BinaryPrecision", g_binary),
    P("MulticlassPrecision", g_multiclass, num_classes=C, average="macro"),
    P("MultilabelPrecision", g_multilabel, num_labels=L),
    P("BinaryRecall", g_binary),
    P("MulticlassRecall", g_multiclass, num_classes=C, average="weighted"),
    P("BinaryF1Score", g_binary),
    P("MulticlassF1Score", g_multiclass, num_classes=C, average="macro"),
    P("MulticlassFBetaScore", g_multiclass, num_classes=C, beta=2.0, average="macro"),
    P("BinarySpecificity", g_binary),
    P("MulticlassSpecificity", g_multiclass, num_classes=C, average="macro"),
    P("BinaryHammingDistance", g_binary),
    P("MulticlassExactMatch", g_exact_match, num_classes=C, multidim_average="global"),
    P("MulticlassStatScores", g_multiclass, num_classes=C, average=None),
    # ---- confusion-matrix family
    P("BinaryConfusionMatrix", g_binary),
    P("MulticlassConfusionMatrix", g_multiclass, num_classes=C),
    P("MulticlassCohenKappa", g_multiclass, num_classes=C),
    P("MulticlassMatthewsCorrCoef", g_multiclass, num_classes=C),
    P("MulticlassJaccardIndex", g_multiclass, num_classes=C),
    # ---- curve family (exact + binned)
    P("BinaryAUROC", g_binary),
    P("BinaryAUROC", g_binary, thresholds=50),
    P("MulticlassAUROC", g_multiclass, num_classes=C),
    P("MulticlassAUROC", g_multiclass, num_classes=C, thresholds=50),
    P("MultilabelAUROC", g_multilabel, num_labels=L),
    P("BinaryAveragePrecision", g_binary),
    P("BinaryAveragePrecision", g_binary, thresholds=50),
    P("MulticlassAveragePrecision", g_multiclass, num_classes=C),
    P("BinaryPrecisionRecallCurve", g_binary, thresholds=20),
    P("BinaryROC", g_binary, thresholds=20),
    P("BinaryCalibrationError", g_binary, n_bins=10, norm="l1"),
    P("MulticlassCalibrationError", g_multiclass, num_classes=C, n_bins=10, norm="l1"),
    P("MulticlassHingeLoss", g_multiclass, num_classes=C),
    # ---- ranking
    P("MultilabelRankingAveragePrecision", g_multilabel, num_labels=L),
    P("MultilabelCoverageError", g_multilabel, num_labels=L),
    P("MultilabelRankingLoss", g_multilabel, num_labels=L),
    # ---- regression
    P("MeanSquaredError", g_regression),
    P("MeanAbsoluteError", g_regression),
    P("MeanAbsolutePercentageError", g_regression_pos),
    P("SymmetricMeanAbsolutePercentageError", g_regression_pos),
    P("WeightedMeanAbsolutePercentageError", g_regression_pos),
    P("MeanSquaredLogError", g_regression_pos),
    P("R2Score", g_regression),
    P("ExplainedVariance", g_regression),
    P("PearsonCorrCoef", g_regression),
    P("SpearmanCorrCoef", g_regression, atol=1e-4),
    P("KendallRankCorrCoef", g_regression, atol=1e-4),
    P("ConcordanceCorrCoef", g_regression),
    P("CosineSimilarity", g_regression_2d),
    P("KLDivergence", g_kldiv),
    P("LogCoshError", g_regression),
    P("MinkowskiDistance", g_regression, p=3.0),
    P("RelativeSquaredError", g_regression),
    P("TweedieDevianceScore", g_regression_pos, power=1.5),
    P("CriticalSuccessIndex", g_binary, threshold=0.5),
    # ---- aggregation
    P("MeanMetric", g_scalar),
    P("SumMetric", g_scalar),
    P("MaxMetric", g_scalar),
    P("MinMetric", g_scalar),
    # ---- text
    P("BLEUScore", g_text_pair, atol=1e-4),
    P("SacreBLEUScore", g_text_pair, atol=1e-4),
    P("CHRFScore", g_text_pair, atol=1e-4),
    P("TranslationEditRate", g_text_pair, atol=1e-4),
    P("ExtendedEditDistance", g_text_single, atol=1e-4),
    P("EditDistance", g_text_single),
    P("CharErrorRate", g_text_single),
    P("WordErrorRate", g_text_single),
    P("MatchErrorRate", g_text_single),
    P("WordInfoLost", g_text_single),
    P("WordInfoPreserved", g_text_single),
    P("Perplexity", g_perplexity),
    P("SQuAD", g_squad),
    # ---- clustering
    P("MutualInfoScore", g_labels),
    P("AdjustedMutualInfoScore", g_labels, atol=1e-4),
    P("NormalizedMutualInfoScore", g_labels),
    P("RandScore", g_labels),
    P("AdjustedRandScore", g_labels),
    P("FowlkesMallowsIndex", g_labels),
    P("HomogeneityScore", g_labels),
    P("CompletenessScore", g_labels),
    P("VMeasureScore", g_labels),
    P("CalinskiHarabaszScore", g_intrinsic),
    P("DaviesBouldinScore", g_intrinsic),
    P("DunnIndex", g_intrinsic),
    # ---- nominal
    P("CramersV", g_labels, num_classes=C),
    P("TschuprowsT", g_labels, num_classes=C),
    P("PearsonsContingencyCoefficient", g_labels, num_classes=C),
    P("TheilsU", g_labels, num_classes=C),
    P("FleissKappa", g_ratings, mode="counts"),
    # ---- retrieval (indexes kwarg)
    P("RetrievalMAP", g_retrieval, retrieval=True),
    P("RetrievalMRR", g_retrieval, retrieval=True),
    P("RetrievalNormalizedDCG", g_retrieval, retrieval=True),
    P("RetrievalPrecision", g_retrieval, retrieval=True, top_k=2),
    P("RetrievalRecall", g_retrieval, retrieval=True, top_k=2),
    P("RetrievalHitRate", g_retrieval, retrieval=True, top_k=2),
    P("RetrievalFallOut", g_retrieval, retrieval=True, top_k=2),
    P("RetrievalRPrecision", g_retrieval, retrieval=True),
    # ---- image (signal)
    P("PeakSignalNoiseRatio", g_image, data_range=1.0),
    P("StructuralSimilarityIndexMeasure", g_image, data_range=1.0, atol=1e-4),
    P("UniversalImageQualityIndex", g_image, atol=1e-4),
    P("SpectralAngleMapper", g_image, atol=1e-4),
    P("ErrorRelativeGlobalDimensionlessSynthesis", g_image, atol=1e-3),
    P("RelativeAverageSpectralError", g_image, atol=1e-3),
    P("TotalVariation", g_image_single, atol=1e-3),
    P("SpatialCorrelationCoefficient", g_image, atol=1e-4),
    # ---- audio
    P("SignalNoiseRatio", g_audio),
    P("ScaleInvariantSignalNoiseRatio", g_audio),
    P("ScaleInvariantSignalDistortionRatio", g_audio),
    P("SignalDistortionRatio", g_audio, atol=1e-2),
    # ---- segmentation
    P("GeneralizedDiceScore", g_segmentation, num_classes=3, atol=1e-4),
    # reference MeanIoU at this snapshot sums per-batch means without dividing
    # by num_batches (segmentation/mean_iou.py:122-126, the `/ num_batches` is
    # commented out upstream); our implementation averages correctly, so the
    # reference result is rescaled for comparison.
    P("MeanIoU", g_segmentation, num_classes=3, atol=1e-4, ref_post=lambda r: r / N_BATCHES),
]


@pytest.mark.parametrize("case", CASES, ids=[c.id for c in CASES])
def test_reference_parity(case):
    rng_o = np.random.default_rng(SEED)
    rng_r = np.random.default_rng(SEED)
    try:
        ours, refm = case.ours(), case.ref()
    except (ImportError, ModuleNotFoundError, RuntimeError) as e:
        pytest.skip(f"reference metric unavailable: {e}")
    for i in range(N_BATCHES):
        args_o = case.gen(rng_o, i)
        args_r = case.gen(rng_r, i)
        if case.kwargs_keys:
            n_pos = len(args_o) - len(case.kwargs_keys)
            kw_o = dict(zip(case.kwargs_keys, args_o[n_pos:]))
            kw_r = dict(zip(case.kwargs_keys, args_r[n_pos:]))
            ours.update(*[_as_jax(a) for a in args_o[:n_pos]], **{k: _as_jax(v) for k, v in kw_o.items()})
            refm.update(*[_as_torch(a) for a in args_r[:n_pos]], **{k: _as_torch(v) for k, v in kw_r.items()})
        else:
            ours.update(*[_as_jax(a) for a in args_o])
            refm.update(*[_as_torch(a) for a in args_r])
    ref_result = refm.compute()
    if case.ref_post is not None:
        ref_result = case.ref_post(ref_result)
    _assert_close(ours.compute(), ref_result, case.atol, case.id)


def test_rouge_parity():
    """ROUGE vs reference (nltk is available)."""
    keys = ("rouge1", "rouge2", "rougeL")  # rougeLsum needs nltk punkt data (no egress)
    try:
        refm = R.text.ROUGEScore(rouge_keys=keys)
    except Exception as e:  # availability probe: nltk raises LookupError, not OSError
        pytest.skip(str(e))
    ours = T.text.ROUGEScore(rouge_keys=keys)
    rng = np.random.default_rng(SEED)
    for i in range(N_BATCHES):
        k = [int(x) for x in rng.integers(0, len(CORPUS_PRED), 2)]
        preds = [CORPUS_PRED[k[0]], CORPUS_PRED[k[1]]]
        tgts = [CORPUS_TGT[k[0]][0], CORPUS_TGT[k[1]][0]]
        ours.update(preds, tgts)
        refm.update(preds, tgts)
    _assert_close(ours.compute(), refm.compute(), 1e-4, "rouge")


def test_pairwise_functional_parity():
    import torchmetrics.functional as RF

    import torchmetrics_tpu.functional as TF

    rng = np.random.default_rng(SEED)
    x = rng.standard_normal((6, 4)).astype(np.float32)
    y = rng.standard_normal((5, 4)).astype(np.float32)
    for name in (
        "pairwise_cosine_similarity",
        "pairwise_euclidean_distance",
        "pairwise_linear_similarity",
        "pairwise_manhattan_distance",
        "pairwise_minkowski_distance",
    ):
        ours = getattr(TF, name)(jnp.asarray(x), jnp.asarray(y))
        ref = getattr(RF, name)(torch.as_tensor(x), torch.as_tensor(y))
        _assert_close(ours, ref, 1e-4, name)


def test_forward_batch_value_parity():
    """Per-batch forward values (not just accumulation) for a core subset."""
    sub = [c for c in CASES if c.id in (
        "BinaryAccuracy", "MulticlassAccuracy[num_classes=5,average=macro]",
        "MeanSquaredError", "PearsonCorrCoef",
    )]
    assert sub
    for case in sub:
        rng_o = np.random.default_rng(SEED)
        rng_r = np.random.default_rng(SEED)
        ours, refm = case.ours(), case.ref()
        for i in range(2):
            args_o = case.gen(rng_o, i)
            args_r = case.gen(rng_r, i)
            bo = ours.forward(*[_as_jax(a) for a in args_o])
            br = refm(*[_as_torch(a) for a in args_r])
            _assert_close(bo, br, 1e-4, f"{case.id}.forward[{i}]")
        _assert_close(ours.compute(), refm.compute(), 1e-4, f"{case.id}.accum")
