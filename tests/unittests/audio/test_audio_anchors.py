"""Value-level numeric anchors for STOI and SRMR (VERDICT r3 #7).

The delegation targets (pystoi, the SRMR toolbox / gammatone) are not
installed in this image and cannot be fetched (zero egress), and the COCO-
style recorded-fixture route is closed for the same reason — so these
anchors pin VALUES analytically instead of by property:

* exact invariances of the STOI definition (identity = 1.0, scale
  invariance) that any transcription error in the correlation core breaks;
* the one-third-octave band matrix against an independent closed form
  (nearest-bin quantized band edges computed by a different formula than
  the implementation's argmin scan) — the exact "sign error in the band
  matrix" blind spot VERDICT r3 weak #4 called out;
* pure tones at band centers must concentrate their energy in THEIR band;
* the gammatone filterbank against the Slaney ERB closed forms: uniform
  ERB-scale spacing, response peaked at cf, and the analytic -3 dB width
  of a 4th-order gammatone;
* amplitude-modulation routing for SRMR: 4 Hz AM energy must land in the
  low modulation bands (SRMR >> 1), 100 Hz AM must not.
"""

import numpy as np
import pytest

from torchmetrics_tpu.functional.audio.srmr import (
    _erb_center_freqs,
    _gammatone_fft_weights,
    speech_reverberation_modulation_energy_ratio as srmr,
)
from torchmetrics_tpu.functional.audio.stoi import (
    FS,
    MINFREQ,
    NFFT,
    NUMBAND,
    _stft_mag,
    _thirdoct,
    short_time_objective_intelligibility as stoi,
)


# ---------------------------------------------------------------------- STOI
@pytest.mark.parametrize("extended", [False, True])
@pytest.mark.parametrize("fs", [10000, 16000])
def test_stoi_identity_is_exactly_one(extended, fs):
    """d(x, x) = 1: every per-segment correlation of identical signals is 1,
    and the clipping bound never engages (y' = min(x, x(1+10^(15/20))) = x)."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=2 * fs)
    assert float(stoi(x, x, fs, extended=extended)) == pytest.approx(1.0, abs=1e-6)


def test_stoi_scale_invariance_is_exact():
    """The per-(segment, band) energy normalization makes classic STOI
    invariant to a global gain on either signal."""
    rng = np.random.default_rng(8)
    x = rng.normal(size=32000)
    assert float(stoi(5.0 * x, x, 16000)) == pytest.approx(1.0, abs=1e-6)
    assert float(stoi(x, 0.1 * x, 16000)) == pytest.approx(1.0, abs=1e-6)


def test_thirdoct_matches_independent_closed_form():
    """Band k spans the FFT bins [round(f_lo/Δ), round(f_hi/Δ)) with
    f_lo = 150·2^((2k-1)/6), f_hi = 150·2^((2k+1)/6), Δ = fs/nfft.

    This recomputes the band edges with round() instead of the
    implementation's argmin scan — a sign/order error in either produces a
    different bin set.
    """
    obm, cf = _thirdoct(FS, NFFT, NUMBAND, MINFREQ)
    k = np.arange(NUMBAND)
    np.testing.assert_allclose(cf, MINFREQ * 2.0 ** (k / 3.0))

    delta = FS / NFFT
    lo_bins = np.round(MINFREQ * 2.0 ** ((2 * k - 1) / 6.0) / delta).astype(int)
    hi_bins = np.round(MINFREQ * 2.0 ** ((2 * k + 1) / 6.0) / delta).astype(int)
    for i in range(NUMBAND):
        np.testing.assert_array_equal(
            np.nonzero(obm[i])[0], np.arange(lo_bins[i], hi_bins[i]),
            err_msg=f"band {i} bin range mismatch",
        )
    # bands tile the axis contiguously: band i ends where band i+1 begins
    assert all(hi_bins[i] == lo_bins[i + 1] for i in range(NUMBAND - 1))


@pytest.mark.parametrize("band", [0, 2, 7, 12, 14])
def test_pure_tone_energy_lands_in_its_band(band):
    """A sinusoid at the k-th third-octave center must put its dominant
    band energy into band k — the direct detector for a transposed or
    sign-flipped band matrix."""
    obm, cf = _thirdoct(FS, NFFT, NUMBAND, MINFREQ)
    t = np.arange(2 * FS) / FS
    tone = np.sin(2 * np.pi * cf[band] * t)
    spec = _stft_mag(tone, 256, 128, NFFT).T
    band_energy = (obm @ spec**2).sum(axis=1)
    assert int(band_energy.argmax()) == band


def test_stoi_known_degradation_values():
    """Additive white noise at fixed SNRs gives reproducible mid-range
    values (seeded), pinned with a tolerance wide enough for BLAS/fft
    variation but far tighter than the property tests' orderings."""
    rng = np.random.default_rng(9)
    x = rng.normal(size=32000)
    noise = rng.normal(size=32000)

    def at_snr(db):
        scaled = noise * np.linalg.norm(x) / np.linalg.norm(noise) * 10 ** (-db / 20)
        return float(stoi(x + scaled, x, 16000))

    dm5, d0, d10 = at_snr(-5.0), at_snr(0.0), at_snr(10.0)
    assert 1.0 > d10 > d0 > dm5 > 0.0
    # recorded from this implementation (seeded, deterministic pipeline);
    # guards against silent numeric drift in any stage
    assert dm5 == pytest.approx(0.192, abs=0.02)
    assert d0 == pytest.approx(0.454, abs=0.02)
    assert d10 == pytest.approx(0.901, abs=0.02)


# ---------------------------------------------------------------------- SRMR
def test_erb_centers_uniform_on_erb_scale():
    """Slaney ERB scale: e(f) = EarQ·ln(1 + f/(EarQ·minBW)).  The center
    frequencies must be EQUALLY spaced on e and bracket (low, high)."""
    ear_q, min_bw = 9.26449, 24.7
    low, high, n = 125.0, 16000 / 2 * 0.9, 23
    cfs = _erb_center_freqs(low, high, n)

    def e(f):
        return ear_q * np.log(1 + f / (ear_q * min_bw))

    steps = np.diff(e(cfs))
    np.testing.assert_allclose(steps, steps[0], rtol=1e-9)
    assert low < cfs[0] < cfs[-1] < high
    # n uniform steps from e(low) span to e(high): cf_k = step positions
    step = (e(high) - e(low)) / n
    np.testing.assert_allclose(steps[0], step, rtol=1e-9)


def test_gammatone_response_peak_and_bandwidth():
    """Each filter's FFT-domain response peaks at its center frequency, and
    its -3 dB full width matches the analytic 4th-order gammatone value:
    |H| = (1+u²)^(-2) = 2^(-1/2)  ->  u = sqrt(2^(1/4) - 1) ≈ 0.4350,
    full width = 2·u·b/(2π) with b = 1.019·2π·ERB(cf)."""
    fs, n = 16000, 16000  # 1 Hz bin resolution
    cfs = _erb_center_freqs(125.0, fs / 2 * 0.9, 23)
    weights = _gammatone_fft_weights(fs, n, cfs)
    freqs = np.fft.rfftfreq(n, 1.0 / fs)

    ear_q, min_bw = 9.26449, 24.7
    erb = ((cfs / ear_q) ** 4 + min_bw**4) ** 0.25
    x3db = np.sqrt(2.0 ** 0.25 - 1.0)
    expected_width = 2 * x3db * (1.019 * 2 * np.pi * erb) / (2 * np.pi)

    for i in range(0, 23, 4):
        resp = weights[i]
        assert abs(freqs[resp.argmax()] - cfs[i]) <= 1.0  # peak at cf (±1 bin)
        above = freqs[resp >= 2 ** (-0.5)]
        measured = above.max() - above.min()
        np.testing.assert_allclose(measured, expected_width[i], rtol=0.05)


def test_srmr_am_modulation_routing():
    """AM at 4 Hz (center of the lowest modulation band) concentrates
    envelope energy in the low bands -> SRMR far above 1; AM at 100 Hz
    (inside the highest band) must not."""
    fs = 16000
    t = np.arange(2 * fs) / fs
    carrier = np.sin(2 * np.pi * 1000 * t)
    am_slow = (1 + 0.9 * np.sin(2 * np.pi * 4 * t)) * carrier
    am_fast = (1 + 0.9 * np.sin(2 * np.pi * 100 * t)) * carrier

    slow = float(srmr(am_slow, fs))
    fast = float(srmr(am_fast, fs))
    assert slow > 100.0, f"4 Hz AM should dominate the low modulation bands, got {slow}"
    assert fast < 2.0, f"100 Hz AM should not, got {fast}"
    assert slow > 100 * fast
