"""Audio metric tests.

Oracles from the reference's doctest outputs
(/root/reference/src/torchmetrics/functional/audio/*.py) using torch to
generate seed-identical inputs.
"""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp
import torch

from torchmetrics_tpu.functional.audio import (
    complex_scale_invariant_signal_noise_ratio,
    permutation_invariant_training,
    pit_permutate,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    short_time_objective_intelligibility,
    signal_distortion_ratio,
    signal_noise_ratio,
    source_aggregated_signal_distortion_ratio,
    speech_reverberation_modulation_energy_ratio,
)
from torchmetrics_tpu.audio import (
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
    SourceAggregatedSignalDistortionRatio,
    SpeechReverberationModulationEnergyRatio,
)

TARGET = jnp.asarray([3.0, -0.5, 2.0, 7.0])
PREDS = jnp.asarray([2.5, 0.0, 2.0, 8.0])


def J(t: torch.Tensor) -> jnp.ndarray:
    return jnp.asarray(t.numpy())


def test_snr_oracle():
    assert float(signal_noise_ratio(PREDS, TARGET)) == pytest.approx(16.1805, abs=1e-4)


def test_si_snr_oracle():
    assert float(scale_invariant_signal_noise_ratio(PREDS, TARGET)) == pytest.approx(15.0918, abs=1e-4)


def test_si_sdr_oracle():
    assert float(scale_invariant_signal_distortion_ratio(PREDS, TARGET)) == pytest.approx(18.4030, abs=1e-4)


def test_c_si_snr_oracle():
    torch.manual_seed(1)
    preds = torch.randn((1, 257, 100, 2))
    target = torch.randn((1, 257, 100, 2))
    got = complex_scale_invariant_signal_noise_ratio(J(preds), J(target))
    assert float(got[0]) == pytest.approx(-63.4849, abs=1e-2)


def test_sdr_oracle():
    torch.manual_seed(1)
    preds = torch.randn(8000)
    target = torch.randn(8000)
    got = float(signal_distortion_ratio(J(preds), J(target)))
    assert got == pytest.approx(-12.0589, abs=1e-2)


def test_sa_sdr_oracle():
    torch.manual_seed(1)
    preds = torch.randn(2, 8000)
    target = torch.randn(2, 8000)
    got = float(source_aggregated_signal_distortion_ratio(J(preds), J(target)))
    assert got == pytest.approx(-41.6579, abs=1e-3)


def test_pit_oracle():
    preds = jnp.asarray([[[-0.0579, 0.3560, -0.9604], [-0.1719, 0.3205, 0.2951]]])
    target = jnp.asarray([[[1.0958, -0.1648, 0.5228], [-0.4100, 1.1942, -0.5103]]])
    best_metric, best_perm = permutation_invariant_training(
        preds, target, scale_invariant_signal_distortion_ratio, "speaker-wise", "max"
    )
    assert float(best_metric[0]) == pytest.approx(-5.1091, abs=1e-3)
    reordered = pit_permutate(preds, best_perm)
    assert reordered.shape == preds.shape


def test_pit_sdr_batch():
    torch.manual_seed(42)
    preds = torch.randn(4, 2, 8000)
    target = torch.randn(4, 2, 8000)
    bm_sw, bp_sw = permutation_invariant_training(
        J(preds), J(target), scale_invariant_signal_distortion_ratio, "speaker-wise", "max"
    )
    bm_pw, bp_pw = permutation_invariant_training(
        J(preds), J(target), scale_invariant_signal_distortion_ratio, "permutation-wise", "max"
    )
    np.testing.assert_allclose(np.asarray(bm_sw), np.asarray(bm_pw), atol=1e-4)
    np.testing.assert_array_equal(np.asarray(bp_sw), np.asarray(bp_pw))


def test_pit_three_speakers_hungarian():
    torch.manual_seed(0)
    preds = torch.randn(2, 3, 100)
    target = torch.randn(2, 3, 100)
    bm, bp = permutation_invariant_training(
        J(preds), J(target), scale_invariant_signal_distortion_ratio, "speaker-wise", "max"
    )
    # brute force check
    from itertools import permutations as it_perms

    for b in range(2):
        best = -np.inf
        for perm in it_perms(range(3)):
            vals = [
                float(scale_invariant_signal_distortion_ratio(J(preds)[b, perm[t]], J(target)[b, t]))
                for t in range(3)
            ]
            best = max(best, np.mean(vals))
        assert float(bm[b]) == pytest.approx(best, abs=1e-4)


def test_pit_three_speakers_jit_and_grad():
    import jax

    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=(2, 3, 200)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(2, 3, 200)), jnp.float32)
    fn = lambda p, t: permutation_invariant_training(p, t, scale_invariant_signal_distortion_ratio)[0]  # noqa: E731
    jit_vals = np.asarray(jax.jit(fn)(p, t))
    np.testing.assert_allclose(jit_vals, np.asarray(fn(p, t)), atol=1e-5)
    g = jax.grad(lambda p, t: fn(p, t).sum())(p, t)
    assert np.isfinite(np.asarray(g)).all() and np.abs(np.asarray(g)).sum() > 0


def test_pit_four_speakers_hungarian_matches_exhaustive():
    from itertools import permutations as it_perms

    rng = np.random.default_rng(1)
    p = jnp.asarray(rng.normal(size=(2, 4, 100)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(2, 4, 100)), jnp.float32)
    bm, bp = permutation_invariant_training(p, t, scale_invariant_signal_distortion_ratio)
    for b in range(2):
        best = -np.inf
        for perm in it_perms(range(4)):
            vals = [
                float(scale_invariant_signal_distortion_ratio(p[b, perm[i]], t[b, i]))
                for i in range(4)
            ]
            best = max(best, np.mean(vals))
        assert float(bm[b]) == pytest.approx(best, abs=1e-4)


def test_srmr_short_signal_and_params():
    rng = np.random.default_rng(2)
    short = jnp.asarray(rng.normal(size=1000), jnp.float32)
    assert np.isfinite(float(speech_reverberation_modulation_energy_ratio(short, 8000)))
    x = jnp.asarray(rng.normal(size=8000), jnp.float32)
    default = float(speech_reverberation_modulation_energy_ratio(x, 8000))
    narrow = float(speech_reverberation_modulation_energy_ratio(x, 8000, max_cf=30.0))
    assert default != narrow
    with pytest.raises(NotImplementedError):
        speech_reverberation_modulation_energy_ratio(x, 8000, fast=True)


def test_stoi_degenerate_returns_floor():
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        v = float(short_time_objective_intelligibility(jnp.zeros(500), jnp.zeros(500), 8000))
    assert v == pytest.approx(1e-5)


def test_stoi_properties():
    rng = np.random.default_rng(0)
    t = np.arange(16000) / 8000.0
    clean = (np.sin(2 * np.pi * 440 * t) * np.hanning(len(t))).astype(np.float32)
    clean += rng.normal(size=clean.shape).astype(np.float32) * 0.05
    noisy_light = clean + rng.normal(size=clean.shape).astype(np.float32) * 0.1
    noisy_heavy = clean + rng.normal(size=clean.shape).astype(np.float32) * 2.0
    s_self = float(short_time_objective_intelligibility(jnp.asarray(clean), jnp.asarray(clean), 8000))
    s_light = float(short_time_objective_intelligibility(jnp.asarray(noisy_light), jnp.asarray(clean), 8000))
    s_heavy = float(short_time_objective_intelligibility(jnp.asarray(noisy_heavy), jnp.asarray(clean), 8000))
    assert s_self == pytest.approx(1.0, abs=1e-6)
    assert s_self >= s_light > s_heavy


def test_srmr_runs():
    rng = np.random.default_rng(1)
    speechish = rng.normal(size=16000).astype(np.float32)
    v = float(speech_reverberation_modulation_energy_ratio(jnp.asarray(speechish), 16000))
    assert np.isfinite(v) and v > 0
    with pytest.raises(ValueError, match="fs"):
        speech_reverberation_modulation_energy_ratio(jnp.zeros(100), 44100)


# ------------------------------------------------------------------- classes
@pytest.mark.parametrize(
    "cls,fn,kwargs",
    [
        (SignalNoiseRatio, signal_noise_ratio, {}),
        (ScaleInvariantSignalNoiseRatio, scale_invariant_signal_noise_ratio, {}),
        (ScaleInvariantSignalDistortionRatio, scale_invariant_signal_distortion_ratio, {}),
    ],
)
def test_class_accumulation(cls, fn, kwargs):
    torch.manual_seed(5)
    a = torch.randn(6, 100)
    b = torch.randn(6, 100)
    m = cls(**kwargs)
    m.update(J(a[:3]), J(b[:3]))
    m.update(J(a[3:]), J(b[3:]))
    want = float(np.mean(np.asarray(fn(J(a), J(b)))))
    assert float(m.compute()) == pytest.approx(want, abs=1e-4)


def test_sdr_class():
    torch.manual_seed(1)
    preds = torch.randn(2, 4000)
    target = torch.randn(2, 4000)
    m = SignalDistortionRatio()
    m.update(J(preds), J(target))
    want = float(np.mean(np.asarray(signal_distortion_ratio(J(preds), J(target)))))
    assert float(m.compute()) == pytest.approx(want, abs=1e-3)


def test_sa_sdr_class():
    torch.manual_seed(1)
    preds = torch.randn(3, 2, 1000)
    target = torch.randn(3, 2, 1000)
    m = SourceAggregatedSignalDistortionRatio()
    m.update(J(preds), J(target))
    want = float(np.mean(np.asarray(source_aggregated_signal_distortion_ratio(J(preds), J(target)))))
    assert float(m.compute()) == pytest.approx(want, abs=1e-4)


def test_pit_class():
    torch.manual_seed(2)
    preds = torch.randn(3, 2, 500)
    target = torch.randn(3, 2, 500)
    m = PermutationInvariantTraining(scale_invariant_signal_distortion_ratio, eval_func="max")
    m.update(J(preds), J(target))
    bm, _ = permutation_invariant_training(
        J(preds), J(target), scale_invariant_signal_distortion_ratio, "speaker-wise", "max"
    )
    assert float(m.compute()) == pytest.approx(float(np.mean(np.asarray(bm))), abs=1e-4)


def test_stoi_class():
    rng = np.random.default_rng(3)
    t = np.arange(16000) / 8000.0
    clean = (np.sin(2 * np.pi * 300 * t)).astype(np.float32) + rng.normal(size=16000).astype(np.float32) * 0.05
    noisy = clean + rng.normal(size=16000).astype(np.float32) * 0.3
    m = ShortTimeObjectiveIntelligibility(fs=8000)
    m.update(jnp.asarray(noisy), jnp.asarray(clean))
    v = float(m.compute())
    assert 0 < v <= 1.0


def test_srmr_class():
    rng = np.random.default_rng(4)
    m = SpeechReverberationModulationEnergyRatio(fs=8000)
    m.update(jnp.asarray(rng.normal(size=(2, 8000)), jnp.float32))
    assert np.isfinite(float(m.compute()))


def test_pesq_gated():
    from torchmetrics_tpu.audio import PerceptualEvaluationSpeechQuality
    from torchmetrics_tpu.functional.audio import perceptual_evaluation_speech_quality
    from torchmetrics_tpu.functional.audio.pesq import _PESQ_AVAILABLE

    with pytest.raises(ValueError, match="fs"):
        PerceptualEvaluationSpeechQuality(fs=44100, mode="wb")
    if not _PESQ_AVAILABLE:
        with pytest.raises(ModuleNotFoundError, match="pesq"):
            perceptual_evaluation_speech_quality(jnp.zeros(8000), jnp.zeros(8000), 16000, "wb")
    # pluggable backend works regardless
    fake_backend = lambda fs, t, p, mode: 3.5  # noqa: E731
    v = perceptual_evaluation_speech_quality(
        jnp.zeros((2, 8000)), jnp.zeros((2, 8000)), 16000, "wb", backend=fake_backend
    )
    np.testing.assert_allclose(np.asarray(v), [3.5, 3.5])
