"""Audio metrics through the 8-device sharded-sync path.

Enrollment of the universal sharded tester for the audio domain (VERDICT r4
next #2): the SNR/SDR family's (Σ value, n) sum states batch-split over the
mesh, psum in-graph, and must compute identically to single-device
accumulation (reference ddp coverage: the `average_metric` ddp cases of
/root/reference/tests/unittests/audio/test_snr.py et al.).
"""

import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity

N = 16  # waveforms per step; 8 devices x 2
T = 128  # samples per waveform


@pytest.fixture()
def waveforms():
    rng = np.random.default_rng(21)
    target = rng.normal(size=(2, N, T)).astype(np.float32)
    noise = rng.normal(size=(2, N, T)).astype(np.float32)
    preds = target + 0.3 * noise
    return preds, target


def _batches(preds, target):
    return [(preds[0], target[0]), (preds[1], target[1])]


@pytest.mark.parametrize(
    "name,kwargs",
    [
        ("SignalNoiseRatio", {}),
        ("SignalNoiseRatio", {"zero_mean": True}),
        ("ScaleInvariantSignalNoiseRatio", {}),
        ("ScaleInvariantSignalDistortionRatio", {}),
    ],
)
def test_sharded_audio(mesh, waveforms, name, kwargs):
    import torchmetrics_tpu.audio as A

    ctor = getattr(A, name)
    assert_sharded_parity(mesh, lambda: ctor(**kwargs), _batches(*waveforms), atol=1e-4, rtol=1e-4)


def test_sharded_sa_sdr(mesh):
    """SA-SDR aggregates over a per-sample sources axis — the batch dim that
    shards must be a genuine (batch, spk, time) leading dim."""
    from torchmetrics_tpu.audio import SourceAggregatedSignalDistortionRatio

    rng = np.random.default_rng(22)
    target = rng.normal(size=(2, N, 2, T)).astype(np.float32)
    preds = target + 0.3 * rng.normal(size=(2, N, 2, T)).astype(np.float32)
    assert_sharded_parity(
        mesh,
        SourceAggregatedSignalDistortionRatio,
        _batches(preds, target),
        atol=1e-4,
        rtol=1e-4,
    )


def test_sharded_snr_matches_analytic_oracle(mesh, waveforms):
    """Sharded ≡ single ≡ the closed-form SNR mean over all waveforms."""
    from torchmetrics_tpu.audio import SignalNoiseRatio

    preds, target = waveforms
    p = preds.reshape(-1, T)
    t = target.reshape(-1, T)
    noise = p - t
    snr = 10 * np.log10((t**2).sum(-1) / (noise**2).sum(-1))
    assert_sharded_parity(
        mesh, SignalNoiseRatio, _batches(preds, target), oracle=float(snr.mean()), atol=1e-4,
        rtol=1e-4,
    )


def test_sharded_sdr(mesh, waveforms):
    """SDR's per-sample value solves a Toeplitz system — heavier graph, same
    sum-state sync contract."""
    from torchmetrics_tpu.audio import SignalDistortionRatio

    preds, target = waveforms
    assert_sharded_parity(
        mesh,
        lambda: SignalDistortionRatio(filter_length=32),
        _batches(preds, target),
        atol=1e-3,
        rtol=1e-3,
    )
