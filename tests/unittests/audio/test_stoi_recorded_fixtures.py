"""STOI against recorded pystoi fixtures (VERDICT r4 next #9).

`tests/fixtures/stoi_recorded.json` holds pystoi outputs for three seeded
degraded-speech signals (generate_fixtures.py fills them wherever pystoi is
installed).  Pending fixtures skip cleanly; the monotonicity of our STOI
over the same three signals is asserted regardless — more degradation must
score lower, which needs no external tool to check.
"""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "..", "fixtures")
sys.path.insert(0, FIXTURES)


def _our_stoi_values():
    from generate_fixtures import stoi_signals

    from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility

    values = {}
    for name, c in stoi_signals().items():
        values[name] = float(
            short_time_objective_intelligibility(
                jnp.asarray(c["degraded"], jnp.float32), jnp.asarray(c["clean"], jnp.float32),
                fs=c["fs"],
            )
        )
    return values


def test_stoi_recorded_pystoi_values():
    with open(os.path.join(FIXTURES, "stoi_recorded.json")) as handle:
        fix = json.load(handle)
    if fix["provenance"] == "pending" or any(c["stoi"] is None for c in fix["cases"].values()):
        pytest.skip("fixture awaiting pystoi regeneration (generate_fixtures.py --write)")
    ours = _our_stoi_values()
    for name, case in fix["cases"].items():
        np.testing.assert_allclose(ours[name], case["stoi"], atol=fix["assert_atol"], err_msg=name)


def test_stoi_fixture_signals_order_correctly():
    """10 dB < more noise < -5 dB: our STOI must rank the fixture signals by
    degradation level (tool-free discriminating check on the same inputs the
    recorded vectors will use)."""
    from generate_fixtures import stoi_signals

    from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility

    ours = _our_stoi_values()
    assert ours["light_noise_10db"] > ours["heavy_noise_0db"] > ours["severe_noise_m5db"], ours
    assert -1.0 <= ours["severe_noise_m5db"] <= 1.0
    clean = stoi_signals()["light_noise_10db"]["clean"]
    identity = float(
        short_time_objective_intelligibility(
            jnp.asarray(clean, jnp.float32), jnp.asarray(clean, jnp.float32), fs=10000
        )
    )
    np.testing.assert_allclose(identity, 1.0, atol=1e-3)
