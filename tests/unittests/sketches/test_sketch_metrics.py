"""Sketch-backed metric states end to end: ``Metric(approx="sketch")`` error
bounds vs the exact path across class counts, bit-exact calibration grid
parity, merge semantics, 8-device sharded sync, auditor/resilience/telemetry
integration, and the default ``approx=None`` path staying untouched."""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity
from torchmetrics_tpu.analysis import audit_metric
from torchmetrics_tpu.classification import (
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryCalibrationError,
    BinaryPrecisionRecallCurve,
    BinaryROC,
    MulticlassAUROC,
    MulticlassCalibrationError,
    MulticlassPrecisionRecallCurve,
)
from torchmetrics_tpu.resilience import StateRestoreError, restore, snapshot
from torchmetrics_tpu.text import DistinctNGrams
from torchmetrics_tpu.utilities.benchmark import sync_bytes_per_chip


@pytest.fixture
def rng():
    return np.random.default_rng(31)


def _binary_batch(rng, n):
    # mildly separable scores so AUROC is away from the 0.5 degenerate point
    t = (rng.random(n) < 0.4).astype(np.int32)
    p = np.clip(rng.normal(0.35 + 0.3 * t, 0.25), 0.0, 1.0).astype(np.float32)
    return jnp.asarray(p), jnp.asarray(t)


def _multiclass_batch(rng, n, c):
    logits = rng.normal(size=(n, c)).astype(np.float32)
    target = rng.integers(0, c, n)
    logits[np.arange(n), target] += 1.0  # signal
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    return jnp.asarray(probs), jnp.asarray(target)


# ------------------------------------------------------------ ctor validation
def test_ctor_validation():
    with pytest.raises(ValueError, match="approx"):
        BinaryAUROC(approx="montecarlo")
    with pytest.raises(ValueError, match="approx_error"):
        BinaryAUROC(approx_error=0.01)  # approx_error without approx
    with pytest.raises(ValueError, match="approx_error"):
        BinaryAUROC(approx="sketch", approx_error=0.7)
    with pytest.raises(ValueError, match="thresholds"):
        BinaryAUROC(thresholds=50, approx="sketch")


# ------------------------------------------------- error bounds, {2,10,1000}
def test_binary_auroc_within_documented_bound(rng):
    p, t = _binary_batch(rng, 4096)
    exact = BinaryAUROC()
    sk = BinaryAUROC(approx="sketch")
    exact_v = float(exact.compute_state(exact.update_state(exact.init_state(), p, t)))
    state = sk.update_state(sk.init_state(), p, t)
    sk_v = float(sk.compute_state(state))
    bound = float(sk._sketch.auc_error_bound(state["score_hist"]))
    assert abs(sk_v - exact_v) <= bound + 1e-6
    assert bound < 0.05  # the bound itself is tight enough to be useful


@pytest.mark.parametrize("num_classes,n", [(10, 2048), (1000, 2048)])
def test_multiclass_auroc_within_documented_bound(rng, num_classes, n):
    p, t = _multiclass_batch(rng, n, num_classes)
    exact = MulticlassAUROC(num_classes=num_classes, validate_args=False)
    sk = MulticlassAUROC(num_classes=num_classes, approx="sketch", validate_args=False)
    exact_v = float(exact.compute_state(exact.update_state(exact.init_state(), p, t)))
    state = sk.update_state(sk.init_state(), p, t)
    sk_v = float(sk.compute_state(state))
    # macro average: error bounded by the mean of the per-class bounds
    bound = float(jnp.mean(sk._sketch.auc_error_bound(state["score_hist"])))
    assert abs(sk_v - exact_v) <= bound + 1e-5


def test_tighter_approx_error_tightens_result(rng):
    p, t = _binary_batch(rng, 4096)
    exact = BinaryAUROC()
    exact_v = float(exact.compute_state(exact.update_state(exact.init_state(), p, t)))
    errs = []
    for eps in (1 / 16, 1 / 256):
        m = BinaryAUROC(approx="sketch", approx_error=eps)
        errs.append(abs(float(m.compute_state(m.update_state(m.init_state(), p, t))) - exact_v))
    assert errs[1] <= errs[0] + 1e-7


# --------------------------------- curve points lie exactly on the exact grid
@pytest.mark.parametrize("ctor", [BinaryPrecisionRecallCurve, BinaryROC, BinaryAveragePrecision])
def test_sketch_curve_equals_binned_at_grid_thresholds(rng, ctor):
    """Boundary tail counts are exact, so a sketch curve must reproduce the
    binned path evaluated at exactly the sketch's grid thresholds."""
    p, t = _binary_batch(rng, 1024)
    sk = ctor(approx="sketch", approx_error=1 / 64)
    n_thresholds = sk._sketch.n_cells
    binned = ctor(thresholds=n_thresholds)
    np.testing.assert_allclose(
        np.asarray(binned.thresholds), np.asarray(sk._sketch.edges), atol=1e-7
    )
    got = sk.compute_state(sk.update_state(sk.init_state(), p, t))
    ref = binned.compute_state(binned.update_state(binned.init_state(), p, t))
    for g, r in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=1e-4)


# ------------------------------------------------- calibration grid parity
def test_calibration_error_grid_match_is_bit_exact(rng):
    p, t = _binary_batch(rng, 2000)
    base = BinaryCalibrationError(n_bins=15)
    sk = BinaryCalibrationError(approx="sketch", approx_error=1 / 15)
    a = base.compute_state(base.update_state(base.init_state(), p, t))
    b = sk.compute_state(sk.update_state(sk.init_state(), p, t))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_multiclass_calibration_grid_match(rng):
    p, t = _multiclass_batch(rng, 512, 5)
    base = MulticlassCalibrationError(num_classes=5, n_bins=20)
    sk = MulticlassCalibrationError(num_classes=5, approx="sketch", approx_error=1 / 20)
    a = base.compute_state(base.update_state(base.init_state(), p, t))
    b = sk.compute_state(sk.update_state(sk.init_state(), p, t))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------- merge semantics
def test_merge_vs_single_stream_and_associativity(rng):
    m = BinaryAUROC(approx="sketch")
    chunks = [_binary_batch(rng, 256) for _ in range(3)]
    parts = [m.update_state(m.init_state(), p, t) for p, t in chunks]
    left = m.merge_states(m.merge_states(parts[0], parts[1]), parts[2])
    right = m.merge_states(parts[0], m.merge_states(parts[1], parts[2]))
    np.testing.assert_array_equal(
        np.asarray(left["score_hist"]), np.asarray(right["score_hist"])
    )
    single = m.update_state(
        m.init_state(),
        jnp.concatenate([c[0] for c in chunks]),
        jnp.concatenate([c[1] for c in chunks]),
    )
    np.testing.assert_array_equal(
        np.asarray(left["score_hist"]), np.asarray(single["score_hist"])
    )


# ------------------------------------------------------ 8-device sharded sync
def test_sharded_binary_auroc_sketch(mesh, rng):
    batches = [tuple(np.asarray(a) for a in _binary_batch(rng, 64)) for _ in range(2)]
    assert_sharded_parity(mesh, lambda: BinaryAUROC(approx="sketch", validate_args=False), batches)


def test_sharded_multiclass_prc_sketch(mesh, rng):
    p, t = _multiclass_batch(rng, 64, 5)
    assert_sharded_parity(
        mesh,
        lambda: MulticlassPrecisionRecallCurve(
            num_classes=5, approx="sketch", approx_error=1 / 32, validate_args=False
        ),
        [(np.asarray(p), np.asarray(t))],
    )


# -------------------------------------------------------------- audit dogfood
def test_audit_sketch_curve_has_zero_gathers(rng):
    p, t = _binary_batch(rng, 64)
    rep = audit_metric(BinaryAUROC(approx="sketch"), p, t)
    assert rep.ok, rep.violations
    assert "ragged-gather" in rep.checks
    assert rep.traced_sync_gathers == 0


def test_audit_exact_curve_skips_gather_check(rng):
    p, t = _binary_batch(rng, 64)
    rep = audit_metric(BinaryAUROC(), p, t)
    assert rep.ok, rep.violations
    assert any(check == "ragged-gather" for check, _ in rep.skipped)


# ------------------------------------------------------ resilience snapshots
def test_sketch_state_snapshot_roundtrip(rng):
    p, t = _binary_batch(rng, 512)
    m = BinaryAUROC(approx="sketch")
    m.update(p, t)
    fresh = BinaryAUROC(approx="sketch")
    restore(fresh, snapshot(m))
    np.testing.assert_array_equal(np.asarray(fresh.compute()), np.asarray(m.compute()))


def test_sketch_state_restore_rejects_wrong_shape(rng):
    """SketchReduce leaves are fixed-shape (not growable cat states): a
    snapshot with a resized histogram must be rejected, not installed."""
    p, t = _binary_batch(rng, 128)
    m = BinaryAUROC(approx="sketch")
    m.update(p, t)
    snap = copy.deepcopy(snapshot(m))
    good = snap["state"]["score_hist"]
    snap["state"]["score_hist"] = np.zeros((*good.shape[:-1], good.shape[-1] + 7), good.dtype)
    with pytest.raises(StateRestoreError):
        restore(BinaryAUROC(approx="sketch"), snap)


# --------------------------------------------------------- byte-cut telemetry
def test_modelled_sync_byte_cut_at_least_5x(rng):
    n = 8192
    p, t = _binary_batch(rng, n)
    exact = BinaryAUROC()
    exact_state = exact.update_state(exact.init_state(), p, t)
    sk = BinaryAUROC(approx="sketch")
    sk_state = sk.update_state(sk.init_state(), p, t)
    exact_b = sync_bytes_per_chip(exact._reductions, dict(exact_state), 8)
    sk_b = sync_bytes_per_chip(sk._reductions, dict(sk_state), 8)
    assert sk_b > 0
    assert exact_b / sk_b >= 5.0, (exact_b, sk_b)


# ----------------------------------------------------- default path untouched
def test_default_path_is_isolated_from_sketch_instances(rng):
    sk = BinaryAUROC(approx="sketch")  # noqa: F841 - must not leak into defaults
    m = BinaryAUROC()
    assert m._sketch is None
    assert m.approx is None
    state = m.init_state()
    assert "score_hist" not in state
    assert set(state) >= {"preds", "target", "weight"}
    # and both results still agree on shared data within the sketch bound
    p, t = _binary_batch(rng, 256)
    assert m.update_state(state, p, t)["preds"][0].shape == (256,)


def test_approx_is_part_of_config_fingerprint():
    a, b = BinaryAUROC(), BinaryAUROC()
    assert a._config_fingerprint() == b._config_fingerprint()
    assert BinaryAUROC(approx="sketch")._config_fingerprint() != a._config_fingerprint()
    assert (
        BinaryAUROC(approx="sketch", approx_error=1 / 64)._config_fingerprint()
        != BinaryAUROC(approx="sketch")._config_fingerprint()
    )


# ------------------------------------------------------------- DistinctNGrams
def test_distinct_ngrams_exact_matches_numpy(rng):
    tokens = rng.integers(0, 50, size=(8, 32)).astype(np.int32)
    m = DistinctNGrams(ngram=2)
    got = float(m.compute_state(m.update_state(m.init_state(), jnp.asarray(tokens))))
    wins = np.stack([tokens[:, :-1], tokens[:, 1:]], -1).reshape(-1, 2)
    truth = len(np.unique(wins, axis=0)) / len(wins)
    assert got == pytest.approx(truth, abs=1e-6)


def test_distinct_ngrams_sketch_within_rse(rng):
    tokens = rng.integers(0, 5000, size=(64, 64)).astype(np.int32)
    exact = DistinctNGrams(ngram=1)
    sk = DistinctNGrams(ngram=1, approx="sketch")
    e = float(exact.compute_state(exact.update_state(exact.init_state(), jnp.asarray(tokens))))
    s = float(sk.compute_state(sk.update_state(sk.init_state(), jnp.asarray(tokens))))
    assert abs(s - e) / e <= 3 * sk._hll.relative_error


def test_distinct_ngrams_sketch_merge_equals_single_stream(rng):
    a = rng.integers(0, 1000, size=(8, 16)).astype(np.int32)
    b = rng.integers(0, 1000, size=(8, 16)).astype(np.int32)
    m = DistinctNGrams(ngram=2, approx="sketch")
    merged = m.merge_states(
        m.update_state(m.init_state(), jnp.asarray(a)),
        m.update_state(m.init_state(), jnp.asarray(b)),
    )
    single = m.update_state(
        m.update_state(m.init_state(), jnp.asarray(a)), jnp.asarray(b)
    )
    np.testing.assert_array_equal(
        np.asarray(merged["registers"]), np.asarray(single["registers"])
    )
