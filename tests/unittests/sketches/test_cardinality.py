"""HyperLogLog / CountMinSketch properties: estimates within the documented
error bounds, masked inserts are no-ops, merge == single-stream, and
``for_error`` sizes the structures to the requested bound."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.sketches import CountMinSketch, HyperLogLog, mix32


@pytest.fixture
def rng():
    return np.random.default_rng(23)


# ---------------------------------------------------------------------- mix32
def test_mix32_deterministic_and_salt_sensitive():
    keys = jnp.arange(100, dtype=jnp.int32)
    a = mix32(keys, 7)
    b = mix32(keys, 7)
    c = mix32(keys, 8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.any(np.asarray(a) != np.asarray(c))
    assert a.dtype == jnp.uint32


# ----------------------------------------------------------------------- HLL
def test_hll_estimate_within_rse(rng):
    hll = HyperLogLog(precision=11)
    true_n = 20_000
    keys = jnp.asarray(rng.choice(1 << 30, size=true_n, replace=False).astype(np.int32))
    regs = hll.insert_batch(hll.init(), keys)
    est = float(hll.estimate(regs))
    assert abs(est - true_n) / true_n <= 3 * hll.relative_error


def test_hll_duplicates_do_not_inflate(rng):
    hll = HyperLogLog(precision=11)
    keys = jnp.asarray(rng.integers(0, 500, size=50_000).astype(np.int32))
    est = float(hll.estimate(hll.insert_batch(hll.init(), keys)))
    # small range hits the linear-counting branch: near-exact
    assert abs(est - 500) / 500 <= 0.05


def test_hll_small_range_linear_counting():
    hll = HyperLogLog(precision=11)
    regs = hll.insert_batch(hll.init(), jnp.arange(10, dtype=jnp.int32))
    assert abs(float(hll.estimate(regs)) - 10) <= 1.0


def test_hll_mask_is_noop():
    hll = HyperLogLog(precision=8)
    keys = jnp.arange(64, dtype=jnp.int32)
    mask = jnp.asarray([True] * 32 + [False] * 32)
    masked = hll.insert_batch(hll.init(), keys, mask=mask)
    half = hll.insert_batch(hll.init(), keys[:32])
    np.testing.assert_array_equal(np.asarray(masked), np.asarray(half))


def test_hll_merge_equals_single_stream(rng):
    hll = HyperLogLog(precision=10)
    a = jnp.asarray(rng.integers(0, 1 << 20, 3000).astype(np.int32))
    b = jnp.asarray(rng.integers(0, 1 << 20, 3000).astype(np.int32))
    merged = hll.merge(hll.insert_batch(hll.init(), a), hll.insert_batch(hll.init(), b))
    single = hll.insert_batch(hll.init(), jnp.concatenate([a, b]))
    np.testing.assert_array_equal(np.asarray(merged), np.asarray(single))


def test_hll_for_error_sizing():
    assert HyperLogLog.for_error(0.01).relative_error <= 0.01
    assert HyperLogLog.for_error(None).precision == 11
    assert HyperLogLog.for_error(0.5).precision == 4  # clamped floor
    with pytest.raises(ValueError):
        HyperLogLog(precision=3)


def test_hll_insert_is_jittable():
    hll = HyperLogLog(precision=8)
    regs = jax.jit(hll.insert_batch)(hll.init(), jnp.arange(100, dtype=jnp.int32))
    assert regs.shape == (256,)


# ----------------------------------------------------------------- count-min
def test_cms_never_undercounts_and_bounded_overcount(rng):
    cms = CountMinSketch.for_error(0.01)
    n = 20_000
    keys = rng.zipf(1.3, size=n).astype(np.int32) % 5000
    table = cms.insert_batch(cms.init(), jnp.asarray(keys))
    probe = np.unique(keys[:200])
    est = np.asarray(cms.query(table, jnp.asarray(probe)))
    true = np.asarray([np.sum(keys == k) for k in probe], np.float32)
    assert np.all(est >= true - 1e-3)  # never undercounts
    assert np.all(est - true <= cms.overcount_fraction * n + 1e-3)


def test_cms_weighted_merge_equals_single_stream(rng):
    cms = CountMinSketch(width=64, depth=3)
    ka = jnp.asarray(rng.integers(0, 100, 500).astype(np.int32))
    kb = jnp.asarray(rng.integers(0, 100, 500).astype(np.int32))
    wa = jnp.asarray(rng.random(500).astype(np.float32))
    wb = jnp.asarray(rng.random(500).astype(np.float32))
    merged = cms.merge(
        cms.insert_batch(cms.init(), ka, wa), cms.insert_batch(cms.init(), kb, wb)
    )
    single = cms.insert_batch(cms.init(), jnp.concatenate([ka, kb]), jnp.concatenate([wa, wb]))
    np.testing.assert_allclose(np.asarray(merged), np.asarray(single), rtol=1e-6)


def test_cms_for_error_sizing():
    cms = CountMinSketch.for_error(0.001, delta=0.01)
    assert cms.overcount_fraction <= 0.001
    assert cms.depth >= 5  # ceil(ln 100)
    with pytest.raises(ValueError):
        CountMinSketch(width=0)
