"""QuantileSketch primitive properties: grid/rank guarantees, exact boundary
tail counts, merge associativity, and merge == single-stream equality."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.sketches import DEFAULT_APPROX_ERROR, QuantileSketch, bins_for_error


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def test_bins_for_error_resolution():
    assert bins_for_error(0.01) == 100
    assert bins_for_error(1.0) == 2  # floor
    with pytest.raises(ValueError):
        bins_for_error(0.0)
    with pytest.raises(ValueError):
        bins_for_error(1.5)


def test_for_error_defaults():
    sk = QuantileSketch.for_error(None)
    assert sk.eps == pytest.approx(DEFAULT_APPROX_ERROR)
    assert QuantileSketch.for_error(1 / 64).bins == 64


def test_quantile_query_within_grid_resolution(rng):
    sk = QuantileSketch.for_error(1 / 512)
    vals = rng.random(50_000).astype(np.float32)
    hist = sk.insert_batch(sk.init(), jnp.asarray(vals))
    for q in (0.1, 0.25, 0.5, 0.9, 0.99):
        got = float(sk.query(hist, q))
        true = float(np.quantile(vals, q))
        assert abs(got - true) <= 2 * sk.eps, (q, got, true)


def test_tail_counts_exact_at_boundaries(rng):
    sk = QuantileSketch(bins=10)
    vals = rng.random(5000).astype(np.float32)
    hist = sk.insert_batch(sk.init(), jnp.asarray(vals))
    tails = np.asarray(sk.tail_counts(hist))
    edges = np.asarray(sk.edges)
    for i, edge in enumerate(edges):
        assert tails[i] == pytest.approx(np.sum(vals >= edge)), i


def test_merge_equals_single_stream(rng):
    sk = QuantileSketch.for_error(0.01)
    a, b = rng.random(1000).astype(np.float32), rng.random(700).astype(np.float32)
    merged = sk.merge(
        sk.insert_batch(sk.init(), jnp.asarray(a)), sk.insert_batch(sk.init(), jnp.asarray(b))
    )
    single = sk.insert_batch(sk.init(), jnp.asarray(np.concatenate([a, b])))
    np.testing.assert_array_equal(np.asarray(merged), np.asarray(single))


def test_merge_associativity(rng):
    sk = QuantileSketch(bins=32)
    hists = [sk.insert_batch(sk.init(), jnp.asarray(rng.random(200).astype(np.float32))) for _ in range(3)]
    left = sk.merge(sk.merge(hists[0], hists[1]), hists[2])
    right = sk.merge(hists[0], sk.merge(hists[1], hists[2]))
    np.testing.assert_array_equal(np.asarray(left), np.asarray(right))


def test_prefix_shaped_and_weighted_insert(rng):
    sk = QuantileSketch(bins=8)
    vals = rng.random((64, 3, 2)).astype(np.float32)  # batch of 64 per (3, 2) row
    w = rng.random((64, 3, 2)).astype(np.float32)
    hist = sk.insert_batch(sk.init((3, 2)), jnp.asarray(vals), jnp.asarray(w))
    assert hist.shape == (3, 2, 9)
    np.testing.assert_allclose(np.asarray(sk.total(hist)), w.sum(0), rtol=1e-5)


def test_insert_is_jit_and_grid_clipping():
    sk = QuantileSketch(bins=4)
    ins = jax.jit(sk.insert_batch)
    hist = ins(sk.init(), jnp.asarray([-1.0, 0.0, 0.5, 1.0, 2.0]))
    total = float(sk.total(hist))
    assert total == 5.0  # out-of-range values clip into the end cells
    assert float(hist[-1]) == 2.0  # 1.0 and 2.0 pin to the last cell


def test_curve_confmat_matches_binned_update(rng):
    from torchmetrics_tpu.functional.classification.precision_recall_curve import (
        _binned_curve_update,
    )

    sk = QuantileSketch(bins=16)
    p = rng.random(500).astype(np.float32)
    t = (rng.random(500) < 0.4).astype(np.int32)
    w = np.ones(500, np.float32)
    pos = sk.insert_batch(sk.init(), jnp.asarray(p[t == 1]))
    neg = sk.insert_batch(sk.init(), jnp.asarray(p[t == 0]))
    hist = jnp.stack([neg, pos])  # (2, bins + 1)
    confmat = np.asarray(sk.curve_confmat(hist))
    ref = np.asarray(_binned_curve_update(jnp.asarray(p), jnp.asarray(t), jnp.asarray(w), sk.edges))
    np.testing.assert_allclose(confmat, ref, atol=1e-4)


def test_auc_error_bound_shrinks_with_bins(rng):
    p = rng.random(2000).astype(np.float32)
    t = (rng.random(2000) < 0.5).astype(np.int32)
    bounds = []
    for bins in (8, 64, 512):
        sk = QuantileSketch(bins=bins)
        pos = sk.insert_batch(sk.init(), jnp.asarray(p[t == 1]))
        neg = sk.insert_batch(sk.init(), jnp.asarray(p[t == 0]))
        bounds.append(float(sk.auc_error_bound(jnp.stack([neg, pos]))))
    assert bounds[0] > bounds[1] > bounds[2]
    assert bounds[2] < 0.01
