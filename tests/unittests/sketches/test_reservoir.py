"""ReservoirSketch properties: deterministic bottom-k sampling, merge ==
single-stream, combine_stacked over many shards, and estimator rescaling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.sketches import EMPTY_PRIORITY, ReservoirSketch


@pytest.fixture
def rng():
    return np.random.default_rng(5)


def _fill(sk, keys, rng):
    records = jnp.asarray(rng.random((len(keys), sk.fields)).astype(np.float32))
    return sk.insert_batch(sk.init(), records, jnp.asarray(np.asarray(keys, np.int32))), records


def test_init_layout():
    sk = ReservoirSketch(capacity=4, fields=2)
    res = sk.init()
    assert res.shape == (4, 3)
    assert np.all(np.asarray(res[:, 0]) == EMPTY_PRIORITY)
    assert int(sk.count(res)) == 0


def test_underfull_keeps_everything(rng):
    sk = ReservoirSketch(capacity=16, fields=1)
    res, records = _fill(sk, np.arange(5), rng)
    assert int(sk.count(res)) == 5
    kept = np.sort(np.asarray(sk.payload(res))[np.asarray(sk.valid_mask(res)), 0])
    np.testing.assert_allclose(kept, np.sort(np.asarray(records)[:, 0]))


def test_bottom_k_is_deterministic_by_key(rng):
    sk = ReservoirSketch(capacity=8, fields=1)
    keys = np.arange(100)
    res, _ = _fill(sk, keys, rng)
    pri = np.asarray(sk.priority(jnp.asarray(keys, jnp.int32)))
    expect = np.sort(pri)[:8]
    np.testing.assert_allclose(np.sort(np.asarray(res[:, 0])), expect, rtol=1e-6)


def test_merge_equals_single_stream(rng):
    sk = ReservoirSketch(capacity=10, fields=2)
    keys = rng.choice(1 << 20, size=200, replace=False)
    records = jnp.asarray(rng.random((200, 2)).astype(np.float32))
    k = jnp.asarray(keys.astype(np.int32))
    a = sk.insert_batch(sk.init(), records[:80], k[:80])
    b = sk.insert_batch(sk.init(), records[80:], k[80:])
    merged = sk.merge(a, b)
    single = sk.insert_batch(sk.init(), records, k)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(single), rtol=1e-6)


def test_combine_stacked_matches_pairwise_folds(rng):
    sk = ReservoirSketch(capacity=6, fields=1)
    keys = rng.choice(1 << 20, size=120, replace=False).astype(np.int32)
    records = rng.random((120, 1)).astype(np.float32)
    shards = [
        sk.insert_batch(sk.init(), jnp.asarray(records[i : i + 30]), jnp.asarray(keys[i : i + 30]))
        for i in range(0, 120, 30)
    ]
    stacked = sk.combine_stacked(jnp.stack(shards))
    folded = shards[0]
    for s in shards[1:]:
        folded = sk.merge(folded, s)
    np.testing.assert_allclose(np.asarray(stacked), np.asarray(folded), rtol=1e-6)


def test_scale_factor_unbiased_sum_estimate(rng):
    sk = ReservoirSketch(capacity=64, fields=1)
    n = 5000
    keys = rng.choice(1 << 24, size=n, replace=False).astype(np.int32)
    vals = rng.random((n, 1)).astype(np.float32)
    res = sk.insert_batch(sk.init(), jnp.asarray(vals), jnp.asarray(keys))
    scale = float(sk.scale_factor(res, jnp.float32(n)))
    est = float(jnp.sum(sk.payload(res)[:, 0] * sk.valid_mask(res))) * scale
    true = float(vals.sum())
    # uniform 64-sample of ~U[0,1]: CLT gives ~12% rel. std err; allow 4 sigma
    assert abs(est - true) / true < 0.5


def test_insert_is_jittable():
    sk = ReservoirSketch(capacity=4, fields=1)
    res = jax.jit(sk.insert_batch)(
        sk.init(), jnp.ones((10, 1), jnp.float32), jnp.arange(10, dtype=jnp.int32)
    )
    assert int(sk.count(res)) == 4


def test_ctor_validation():
    with pytest.raises(ValueError):
        ReservoirSketch(capacity=0, fields=1)
    with pytest.raises(ValueError):
        ReservoirSketch(capacity=1, fields=0)
