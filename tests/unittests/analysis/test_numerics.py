"""Tier-4 numerics sanitizer tests (TMT014-TMT017).

Covers the Abstract interval/exactness domain on real jaxprs, horizon
prediction (including an *empirical* int16 wrap matching the static
prediction within one batch), the four finding families on deliberately
broken metrics, suppression/hygiene integration, and the value_range
snapshot/fingerprint round-trip.
"""

import math
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.analysis.linter import apply_suppressions, lint_file
from torchmetrics_tpu.analysis.numerics import (
    NUMERICS_RULE_IDS,
    Abstract,
    NumericsAssumptions,
    _compression_findings,
    _divide_findings,
    _horizon_findings,
    _range_contract_findings,
    _trace_update,
    abstract_eval_jaxpr,
    format_horizon_table,
    mantissa_bits,
    predict_horizons,
)
from torchmetrics_tpu.analysis.linter import all_rules
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.utilities.compute import _safe_divide

pytestmark = pytest.mark.numerics

INF = float("inf")


def _abstract_of(fn, *in_abstracts, example_args):
    closed = jax.make_jaxpr(fn)(*example_args)
    outs, _ev = abstract_eval_jaxpr(closed, list(in_abstracts))
    return outs[0]


# ------------------------------------------------------------ abstract domain
def test_interval_add_sub_mul():
    x = jnp.zeros((4,))
    out = _abstract_of(lambda a, b: a + b, Abstract(0, 2, True), Abstract(1, 3, True), example_args=(x, x))
    assert (out.lo, out.hi, out.integral) == (1, 5, True)
    out = _abstract_of(lambda a, b: a - b, Abstract(0, 2, True), Abstract(1, 3, True), example_args=(x, x))
    assert (out.lo, out.hi) == (-3, 1)
    out = _abstract_of(lambda a, b: a * b, Abstract(-2, 3, True), Abstract(0, 4, True), example_args=(x, x))
    assert (out.lo, out.hi, out.integral) == (-8, 12, True)


def test_comparison_yields_unit_integral_indicator():
    x = jnp.zeros((4,))
    out = _abstract_of(lambda a, b: (a >= b).astype(jnp.float32), Abstract(-INF, INF, False),
                       Abstract(-INF, INF, False), example_args=(x, x))
    assert (out.lo, out.hi, out.integral) == (0, 1, True)


def test_square_and_same_var_mul_are_nonnegative():
    x = jnp.zeros((4,))
    top = Abstract(-INF, INF, False)
    assert _abstract_of(lambda a: jnp.square(a), top, example_args=(x,)).lo == 0
    assert _abstract_of(lambda a: a * a, top, example_args=(x,)).lo == 0


def test_reduce_sum_scales_by_element_count():
    x = jnp.zeros((8,))
    out = _abstract_of(lambda a: jnp.sum((a >= 0).astype(jnp.float32)), Abstract(-INF, INF, False),
                       example_args=(x,))
    assert (out.lo, out.hi, out.integral) == (0, 8, True)


def test_clip_and_maximum_bound_the_interval():
    x = jnp.zeros((4,))
    top = Abstract(-INF, INF, False)
    out = _abstract_of(lambda a: jnp.clip(a, 0.0, 1.0), top, example_args=(x,))
    assert (out.lo, out.hi) == (0, 1)
    out = _abstract_of(lambda a: jnp.maximum(a, 1.0), top, example_args=(x,))
    assert out.lo == 1


def test_int_cast_clamps_to_dtype_range():
    x = jnp.zeros((4,))
    out = _abstract_of(lambda a: a.astype(jnp.int8), Abstract(-INF, INF, False), example_args=(x,))
    assert (out.lo, out.hi, out.integral) == (-128, 127, True)


def test_mantissa_bits():
    assert mantissa_bits(jnp.float32) == 24
    assert mantissa_bits(jnp.bfloat16) == 8
    assert mantissa_bits(jnp.float16) == 11


# ----------------------------------------------------------- horizon metrics
class _Counter(Metric):
    """Counts elements into a configurable accumulator dtype."""

    def __init__(self, dtype=jnp.float32, **kwargs):
        super().__init__(**kwargs)
        self.add_state("count", jnp.zeros((), dtype=dtype), dist_reduce_fx="sum")

    def _update(self, state, x):
        # pin the sum dtype: jnp.sum would silently promote int16 to int32
        ones = jnp.ones(x.shape, state["count"].dtype)
        return {"count": state["count"] + jnp.sum(ones, dtype=state["count"].dtype)}

    def _compute(self, state):
        return state["count"]


def _batch(n=32):
    return (jnp.zeros((n,), jnp.float32),)


def test_float32_counter_stagnates_at_2_pow_24():
    rows = predict_horizons(_Counter(jnp.float32), *_batch())
    row = next(r for r in rows if r.leaf == "count")
    assert row.kind == "stagnation"
    assert row.rate_per_sample == 1
    assert row.horizon_samples == 2**24


def test_int16_counter_saturates_at_iinfo_max():
    rows = predict_horizons(_Counter(jnp.int16), *_batch())
    row = next(r for r in rows if r.leaf == "count")
    assert row.kind == "saturation"
    assert row.horizon_samples == np.iinfo(np.int16).max


def test_horizon_findings_respect_sample_budget():
    m = _Counter(jnp.float32)
    rows = predict_horizons(m, *_batch())
    hot = NumericsAssumptions(sample_budget=1e9)
    cold = NumericsAssumptions(sample_budget=1e6)
    assert any(f.rule == "TMT014" for f in _horizon_findings(m, rows, hot))
    assert not _horizon_findings(m, rows, cold)


def test_int32_counter_clears_default_budget():
    m = _Counter(jnp.int32)
    rows = predict_horizons(m, *_batch())
    assert not _horizon_findings(m, rows, NumericsAssumptions())


def test_predicted_int16_horizon_matches_observed_wrap():
    """Satellite: the static horizon is not just plausible — run a deliberately
    small int16 accumulator to its predicted wrap and check the observed
    overflow lands within one batch of the prediction."""
    batch = 4096
    m = _Counter(jnp.int16)
    x = jnp.zeros((batch,), jnp.float32)
    row = next(r for r in predict_horizons(m, x) if r.leaf == "count")
    predicted_updates = math.ceil(row.horizon_samples / batch)

    state = m.init_state()
    observed = None
    for step in range(1, predicted_updates + 2):
        state = m.update_state(state, x)
        if int(state["count"]) < step * batch:  # wrapped (or stuck): no longer exact
            observed = step
            break
    assert observed is not None
    assert abs(observed - row.horizon_samples / batch) <= 1.0


# ----------------------------------------------------------- TMT016: divides
class _UnguardedRate(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("hits", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("misses", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        hit = jnp.sum((x >= 0).astype(jnp.float32))
        return {"hits": state["hits"] + hit, "misses": state["misses"] + (x.shape[0] - hit)}

    def _compute(self, state):
        # misses can be exactly zero after updates: this divide is reachable
        return state["hits"] / state["misses"]


class _GuardedRate(_UnguardedRate):
    def _compute(self, state):
        return _safe_divide(state["hits"], state["misses"])


class _MaxBoundedRate(_UnguardedRate):
    def _compute(self, state):
        return state["hits"] / jnp.maximum(state["misses"], 1.0)


def test_unguarded_divide_fires_and_guards_clear_it():
    bad = _UnguardedRate()
    analysis = _trace_update(bad, _batch())
    findings = _divide_findings(bad, analysis)
    assert any(f.rule == "TMT016" for f in findings)
    for cls in (_GuardedRate, _MaxBoundedRate):
        m = cls()
        assert not _divide_findings(m, _trace_update(m, _batch())), cls.__name__


# ----------------------------------------------------- TMT017: range contract
class _BadRange(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        # signed values flow into a leaf declared nonnegative: not inductive
        self.add_state("acc", jnp.zeros(()), dist_reduce_fx="sum", value_range=(0.0, INF))

    def _update(self, state, x):
        return {"acc": state["acc"] + jnp.sum(x)}

    def _compute(self, state):
        return state["acc"]


def test_range_contract_catches_non_inductive_declaration():
    findings = _range_contract_findings(_BadRange(), _batch())
    assert any(f.rule == "TMT017" for f in findings)


def test_range_contract_accepts_inductive_declaration():
    assert not _range_contract_findings(_Counter(jnp.int32), _batch())
    # and metrics with no declarations are trivially clean
    assert not _range_contract_findings(_UnguardedRate(), _batch())


# --------------------------------------------------- TMT015: unsafe downcast
class _WideCounter(Metric):
    """2048-element float32 counter — big enough to clear the bucket floor."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("counts", jnp.zeros((2048,), jnp.float32), dist_reduce_fx="sum")

    def _update(self, state, x):
        return {"counts": state["counts"] + jnp.ones((2048,), jnp.float32)}

    def _compute(self, state):
        return state["counts"]


def test_exact_counter_in_quantized_bucket_fires():
    from torchmetrics_tpu.parallel.coalesce import SyncPolicy

    m = _WideCounter()
    m._autotuned_policy = SyncPolicy(compression="bf16")
    findings = _compression_findings(m, _trace_update(m, _batch()))
    assert any(f.rule == "TMT015" and "exact counter" in f.message for f in findings)


def test_infeasible_error_budget_fires():
    from torchmetrics_tpu.parallel.coalesce import SyncPolicy
    from torchmetrics_tpu.parallel.compress import predicted_error_bound

    m = _WideCounter()
    tiny = predicted_error_bound("int8", stages=2) / 10
    m._autotuned_policy = SyncPolicy(compression="int8", error_budget=tiny)
    findings = _compression_findings(m, _trace_update(m, _batch()))
    assert any(f.rule == "TMT015" and "infeasible" in f.message for f in findings)


def test_uncompressed_policy_is_exempt():
    from torchmetrics_tpu.parallel.coalesce import SyncPolicy

    m = _WideCounter()
    m._autotuned_policy = SyncPolicy(every_n_steps=4)  # compression="none"
    assert not _compression_findings(m, _trace_update(m, _batch()))


# ------------------------------------------------- registry + suppressions
def test_numerics_rules_are_registered_whole_program():
    by_id = {r.id: r for r in all_rules()}
    for rid in NUMERICS_RULE_IDS:
        assert rid in by_id
        assert by_id[rid].whole_program


def test_suppression_filters_numerics_findings(tmp_path):
    from torchmetrics_tpu.analysis.linter import Finding

    src = tmp_path / "mod.py"
    src.write_text(
        "x = 1\n"
        "y = 2  # tmt: ignore[TMT014] -- documented horizon\n"
    )
    findings = [
        Finding("TMT014", "mod.py", 2, "suppressed"),
        Finding("TMT014", "mod.py", 1, "survives"),
        Finding("TMT016", "mod.py", 2, "wrong id, survives"),
    ]
    out = apply_suppressions(findings, root=tmp_path)
    assert [(f.rule, f.line) for f in out] == [("TMT014", 1), ("TMT016", 2)]


def test_hygiene_accepts_numerics_ids_without_staleness(tmp_path):
    """TMT009 hygiene: per-line ignores naming whole-program numerics ids are
    legal in per-file lint runs (their findings only exist in --audit-all),
    but unknown ids and missing justifications still trip."""
    good = tmp_path / "good.py"
    good.write_text("state = 0  # tmt: ignore[TMT014] -- pixel counter, documented horizon\n")
    assert lint_file(good, tmp_path) == []

    nojust = tmp_path / "nojust.py"
    nojust.write_text("state = 0  # tmt: ignore[TMT017]\n")
    assert any(f.rule == "TMT009" and "justification" in f.message for f in lint_file(nojust, tmp_path))

    unknown = tmp_path / "unknown.py"
    unknown.write_text("state = 0  # tmt: ignore[TMT099] -- nope\n")
    assert any(f.rule == "TMT009" and "unknown" in f.message for f in lint_file(unknown, tmp_path))


# ------------------------------------------- value_range snapshot round-trip
def test_value_range_survives_pickle():
    m = _BadRange()
    assert m._value_ranges == {"acc": (0.0, INF)}
    m2 = pickle.loads(pickle.dumps(m))
    assert m2._value_ranges == {"acc": (0.0, INF)}


def test_setstate_defaults_value_ranges_for_old_pickles():
    m = _Counter(jnp.int32)
    state = m.__getstate__()
    state.pop("_value_ranges", None)  # simulate a pre-value_range pickle
    m2 = _Counter.__new__(_Counter)
    m2.__setstate__(state)
    assert m2._value_ranges == {}


def test_value_range_participates_in_config_fingerprint():
    from torchmetrics_tpu.core.compile import config_fingerprint

    class _Ranged(Metric):
        def __init__(self, hi, **kwargs):
            super().__init__(**kwargs)
            self.add_state("ids", [], dist_reduce_fx="cat", value_range=(0.0, float(hi)))

        def _update(self, state, x):
            return {"ids": tuple(state["ids"]) + (x.astype(jnp.int32),)}

        def _compute(self, state):
            return jnp.zeros(())

    a, b, c = _Ranged(255), _Ranged(65535), _Ranged(255)
    assert config_fingerprint(a) == config_fingerprint(c)
    assert config_fingerprint(a) != config_fingerprint(b)


# ----------------------------------------------------------- report surface
def test_github_format_covers_numerics_rules():
    from torchmetrics_tpu.analysis.linter import Finding, format_github

    m = _Counter(jnp.float32)
    rows = predict_horizons(m, *_batch())
    findings = _horizon_findings(m, rows, NumericsAssumptions())
    assert findings
    text = format_github(findings + [Finding("TMT016", "a.py", 3, "divide")])
    assert "title=TMT014" in text and "title=TMT016" in text
    assert text.splitlines()[0].startswith("::error file=")


def test_format_horizon_table_lists_rows():
    rows = predict_horizons(_Counter(jnp.float32), *_batch())
    text = format_horizon_table(rows, NumericsAssumptions(batch_size=4096))
    assert "metric" in text and "horizon (samples)" in text
    assert "_Counter" in text and "stagnation" in text


@pytest.mark.contracts
def test_golden_slate_is_numerics_clean():
    """Dogfood acceptance: the shipped metrics carry no unsuppressed
    TMT014-TMT017 findings (the two documented suppressions excepted)."""
    from torchmetrics_tpu.analysis.numerics import run_numerics_pass

    findings = apply_suppressions(run_numerics_pass())
    assert findings == [], [f"{f.path}:{f.line} {f.rule} {f.message}" for f in findings]
