"""TMT013 golden trace-contract snapshots.

The CI gate: the golden slate re-traces clean against the JSON snapshots in
``tests/unittests/analysis/contracts/``, and a tampered golden fails with a
diff that names the metric and the changed primitive — so a graph regression
reads as "``add`` count 3 -> 4 in BinaryAccuracy update", not a bare assert.
"""

import copy
import json
import shutil

import pytest

from torchmetrics_tpu.analysis.contracts import (
    CONTRACT_SCHEMA_VERSION,
    check_contracts,
    contract_dir,
    diff_contracts,
    golden_graphs,
    golden_metrics,
    trace_contract,
    write_contracts,
)

pytestmark = pytest.mark.contracts


def test_golden_slate_covers_at_least_12_metrics():
    slate = golden_metrics()
    assert len(slate) >= 12
    # families: classification, aggregation, regression, image
    assert {"BinaryAccuracy", "MeanMetric", "MeanSquaredError", "PeakSignalNoiseRatio"} <= set(slate)


def test_snapshots_exist_for_every_slate_entry():
    on_disk = {p.stem for p in contract_dir().glob("*.json")}
    assert set(golden_metrics()) <= on_disk
    assert set(golden_graphs()) <= on_disk


@pytest.mark.catstate
def test_sketch_map_sync_golden_is_gather_free():
    golden = json.loads((contract_dir() / "SketchMAPSync.json").read_text())
    colls = golden["entrypoints"]["sync"]["collectives"]
    assert colls and all("psum" in c for c in colls)
    assert not any("gather" in c for c in colls)


@pytest.mark.catstate
def test_two_stage_golden_pins_byte_model_and_gather():
    golden = json.loads((contract_dir() / "RaggedGatherTwoStageICI.json").read_text())
    colls = golden["entrypoints"]["sync"]["collectives"]
    assert any("all_gather" in c or "pgather" in c for c in colls)
    model = golden["byte_model"]
    # the 8x8 reference: cross-host bytes scale with hosts, not chips
    assert 0 < model["two_stage"] < model["flat"]
    assert model["flat"] == 9 * model["two_stage"]  # (n-1)/(n_hosts-1) = 63/7


def test_snapshot_shape():
    golden = json.loads((contract_dir() / "BinaryAccuracy.json").read_text())
    assert golden["schema"] == CONTRACT_SCHEMA_VERSION
    assert golden["mesh"] == "cpu:8/data"
    update = golden["entrypoints"]["update"]
    sync = golden["entrypoints"]["sync"]
    assert update["primitives"] and sync["primitives"]
    assert update["collectives"] == []  # update path must stay collective-free
    assert sync["collectives"]  # sync must actually cross replicas
    assert update["donation"]["donates"] is True


def test_check_contracts_passes_on_disk_goldens():
    assert check_contracts() == []


def test_tampered_golden_names_metric_and_primitive(tmp_path):
    for p in contract_dir().glob("*.json"):
        shutil.copy(p, tmp_path / p.name)
    target = tmp_path / "BinaryAccuracy.json"
    golden = json.loads(target.read_text())
    prims = golden["entrypoints"]["update"]["primitives"]
    prim = sorted(prims)[0]
    prims[prim] += 1
    target.write_text(json.dumps(golden))
    diffs = check_contracts(tmp_path)
    assert any("BinaryAccuracy" in d and f"primitive '{prim}'" in d for d in diffs)


def test_missing_and_stale_snapshots_are_reported(tmp_path):
    for p in contract_dir().glob("*.json"):
        shutil.copy(p, tmp_path / p.name)
    (tmp_path / "BinaryAccuracy.json").unlink()
    (tmp_path / "RetiredMetric.json").write_text("{}")
    diffs = check_contracts(tmp_path)
    assert any("BinaryAccuracy" in d and "--update-contracts" in d for d in diffs)
    assert any("RetiredMetric" in d and "stale" in d for d in diffs)


def test_update_contracts_roundtrip(tmp_path):
    written = write_contracts(tmp_path, names=["MeanMetric"])
    assert [p.name for p in written] == ["MeanMetric.json"]
    assert json.loads(written[0].read_text()) == json.loads(
        (contract_dir() / "MeanMetric.json").read_text()
    )


def test_trace_contract_is_deterministic():
    metric, inputs = golden_metrics()["SumMetric"]()
    a = trace_contract(metric, *inputs)
    metric2, inputs2 = golden_metrics()["SumMetric"]()
    b = trace_contract(metric2, *inputs2)
    assert a == b


# ------------------------------------------- autotuned policy contract shapes
def test_slate_includes_committed_policy_shapes():
    """The slate snapshots each committed-policy shape the autotuner can
    install: cadence-only, bf16, and int8 next to the exact baseline."""
    slate = golden_metrics()
    assert {
        "BinaryCalibrationError1024",
        "BinaryCalibrationError1024__bf16",
        "BinaryCalibrationError1024__int8",
        "MulticlassAccuracy__every4",
    } <= set(slate)


def test_committed_policy_never_changes_the_update_segment():
    """A policy transition must only reshape the *sync* segment: the update
    trace of every autotuned entry is identical to its exact baseline, and
    the baseline goldens carry no policy key at all."""
    load = lambda name: json.loads((contract_dir() / f"{name}.json").read_text())
    base, bf16, int8 = (
        load("BinaryCalibrationError1024"),
        load("BinaryCalibrationError1024__bf16"),
        load("BinaryCalibrationError1024__int8"),
    )
    assert "policy" not in base
    assert bf16["policy"]["compression"] == "bf16"
    assert int8["policy"]["compression"] == "int8"
    up = lambda c: c["entrypoints"]["update"]
    sync = lambda c: c["entrypoints"]["sync"]
    assert up(base) == up(bf16) == up(int8)
    # ...while the compressed sync segments genuinely lower differently
    assert sync(bf16) != sync(base) and sync(int8) != sync(base)
    assert sync(bf16) != sync(int8)
    # a cadence-only policy is invisible to BOTH segments (every_n is host-side)
    ev4, plain = load("MulticlassAccuracy__every4"), load("MulticlassAccuracy")
    assert ev4["policy"] == {
        "every_n": 4,
        "at_compute": False,
        "compression": "none",
        "error_budget": None,
    }
    assert up(ev4) == up(plain) and sync(ev4) == sync(plain)


def test_armed_accuracy_plane_never_changes_either_segment():
    """The attested golden entry (accuracy plane armed around the trace) is
    byte-identical to the plain committed-policy entry in BOTH segments:
    attestation reads host-side config only and must never reshape a trace."""
    load = lambda name: json.loads((contract_dir() / f"{name}.json").read_text())
    plain = load("BinaryCalibrationError1024__int8")
    attested = load("BinaryCalibrationError1024__int8__attested")
    assert "attested" not in plain
    assert attested["attested"] is True
    assert attested["policy"] == plain["policy"]
    assert attested["entrypoints"] == plain["entrypoints"]


# -------------------------------------------------------------- diff surface
def _contract():
    metric, inputs = golden_metrics()["BinaryAccuracy"]()
    return trace_contract(metric, *inputs)


def test_diff_reports_collective_sequence_change():
    golden = _contract()
    current = copy.deepcopy(golden)
    current["entrypoints"]["sync"]["collectives"].append("all_gather[8:float32]")
    diffs = diff_contracts(golden, current)
    assert any("collective sequence changed" in d and "all_gather" in d for d in diffs)


def test_diff_reports_dropped_donation():
    golden = _contract()
    current = copy.deepcopy(golden)
    current["entrypoints"]["update"]["donation"]["donates"] = False
    diffs = diff_contracts(golden, current)
    assert any("donation mask changed" in d for d in diffs)


def test_diff_identical_contracts_is_empty():
    golden = _contract()
    assert diff_contracts(golden, copy.deepcopy(golden)) == []
