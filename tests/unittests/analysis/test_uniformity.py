"""TMT012 collective-uniformity verifier.

Every sync lowering — plain, coalesced, int8/bf16 compressed, cadence-
windowed, ragged — must issue a replica-independent collective sequence; a
collective under traced control flow deadlocks a real pod.  All paths run
on the 8-device host-platform mesh the test session pins.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from torchmetrics_tpu.analysis.audit import _default_mesh
from torchmetrics_tpu.analysis.uniformity import (
    collective_sequence,
    verify_cadence_step,
    verify_collection_sync,
    verify_metric_sync,
    verify_ragged_gather,
    verify_two_stage_gather,
    verify_uniform,
)
from torchmetrics_tpu.classification import BinaryAccuracy
from torchmetrics_tpu.core.compile import shard_map
from torchmetrics_tpu.regression import MeanSquaredError

pytestmark = pytest.mark.lint


def _binary_batch():
    rng = np.random.default_rng(0)
    return (
        jnp.asarray(rng.random(32, dtype="float32")),
        jnp.asarray(rng.integers(0, 2, 32).astype("int32")),
    )


def _regression_batch():
    rng = np.random.default_rng(1)
    return (
        jnp.asarray(rng.random(32, dtype="float32")),
        jnp.asarray(rng.random(32, dtype="float32")),
    )


def _slate():
    acc, mse = BinaryAccuracy(), MeanSquaredError()
    states = [
        acc.update_state(acc.init_state(), *_binary_batch()),
        mse.update_state(mse.init_state(), *_regression_batch()),
    ]
    return [acc, mse], states


# --------------------------------------------------------------- plain paths
def test_metric_sync_plain_and_compressed_are_uniform():
    # MSE carries a float32 sum leaf (measure) — the compressed paths must
    # engage the wire dtypes for it and stay uniform
    report = verify_metric_sync(MeanSquaredError(), *_regression_batch())
    assert report.ok, report.problems
    assert report.sequences["sync"]  # plain path issues collectives
    int8_seq = " ".join(report.sequences["sync[int8]"])
    bf16_seq = " ".join(report.sequences["sync[bf16]"])
    assert "uint8" in int8_seq or "int8" in int8_seq
    assert "bfloat16" in bf16_seq


def test_integer_counter_sync_never_quantizes():
    # BinaryAccuracy's tp/fp/tn/fn are int32 counters (TMT014 widening):
    # integer buckets must ride the plain psum even under a compression
    # config — quantizing exact counts would corrupt them
    report = verify_metric_sync(BinaryAccuracy(), *_binary_batch())
    assert report.ok, report.problems
    int8_seq = " ".join(report.sequences["sync[int8]"])
    assert "uint8" not in int8_seq and "int8" not in int8_seq
    assert "int32" in int8_seq


def test_coalesced_and_cadence_flush_are_uniform():
    metrics, states = _slate()
    report = verify_collection_sync(metrics, states)
    assert report.ok, report.problems
    assert report.sequences["coalesced"]
    # the every_n cadence flush lowers the same fused collective sequence
    assert report.sequences["cadence-flush"] == report.sequences["coalesced"]


def test_cadence_local_step_is_collective_free():
    metrics, states = _slate()
    report = verify_cadence_step(metrics, states, *_binary_batch())
    assert report.ok, report.problems
    assert all(seq == () for seq in report.sequences.values())


def test_ragged_gather_is_uniform_and_gathers():
    report = verify_ragged_gather()
    assert report.ok, report.problems
    joined = " ".join(seq for seqs in report.sequences.values() for seq in seqs)
    assert "all_gather" in joined or "pgather" in joined


@pytest.mark.catstate
def test_two_stage_gather_ici_is_uniform_and_route_free():
    report = verify_two_stage_gather()
    assert report.ok, report.problems
    # the device-side stage gathers; the DCN stage is recorded as host-side
    assert any("all_gather" in d or "pgather" in d for d in report.sequences["ici-stage"])
    (dcn,) = report.sequences["dcn-stage"]
    assert dcn.startswith("host:process_allgather")


# ------------------------------------------------------- synthetic violation
def test_guarded_collective_is_rejected():
    mesh = _default_mesh(None, "data")
    n_dev = int(mesh.devices.size)

    def bad(x):
        # collective inside a cond dominated by a traced value: some
        # replicas enter the branch, others don't — deadlock shape
        return jax.lax.cond(
            x[0, 0] > 0,
            lambda v: jax.lax.psum(v, "data"),
            lambda v: v,
            x,
        )

    wrapped = shard_map(bad, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False)
    jx = jax.make_jaxpr(wrapped)(jnp.zeros((n_dev, 4)))
    problems = verify_uniform(jx, label="synthetic")
    assert problems
    assert any("psum" in p for p in problems)


def test_unguarded_collective_passes():
    mesh = _default_mesh(None, "data")
    n_dev = int(mesh.devices.size)

    def good(x):
        return jax.lax.psum(x, "data")

    wrapped = shard_map(good, mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False)
    jx = jax.make_jaxpr(wrapped)(jnp.zeros((n_dev, 4)))
    assert verify_uniform(jx, label="synthetic") == []
    seq = collective_sequence(jx)
    assert [op.primitive for op in seq] == ["psum"]


# ----------------------------------------------------- sharded sync (TMT012)
@pytest.mark.sharding
def test_sharded_sync_lowers_reduce_scatter_per_sharded_bucket():
    from torchmetrics_tpu import Metric
    from torchmetrics_tpu.analysis.uniformity import verify_sharded_sync

    class ShardedVec(Metric):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state(
                "vec", jnp.zeros((64,), jnp.float32), dist_reduce_fx="sum",
                state_sharding="sharded",
            )

        def _update(self, state, x):
            return {"vec": state["vec"] + x.sum(axis=0)}

        def _compute(self, state):
            return state["vec"].sum()

    x = jnp.asarray(np.random.default_rng(2).random((8, 64), dtype="float32"))
    report = verify_sharded_sync(ShardedVec(), x)
    assert report.problems == []
    sync_ops = [d.split("[", 1)[0] for d in report.sequences["sync"]]
    assert "reduce_scatter" in sync_ops or "psum_scatter" in sync_ops
    # compressed variants keep the scatter/all_to_all lowering (checked inside
    # verify_sharded_sync; an empty problems list covers both wire modes)
    assert "sync[bf16]" in report.sequences and "sync[int8]" in report.sequences


@pytest.mark.sharding
def test_sharded_verifier_flags_replicated_metric():
    from torchmetrics_tpu.analysis.uniformity import verify_sharded_sync

    report = verify_sharded_sync(MeanSquaredError(), *_regression_batch())
    assert any("no state_sharding specs installed" in p for p in report.problems)
