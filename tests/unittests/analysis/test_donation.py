"""TMT010 donation/aliasing race detector.

The load-bearing regression: PR 1's aliased-donation bug — compute-group
members sharing one state buffer while each donates it on update.  The
healthy package guards this with ``_state_shared`` (``MetricCollection.
_mark_shared``); stripping the guard must reproduce the finding, one per
shared leaf.
"""

import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.analysis.donation import (
    audit_donation,
    donation_mask,
    scan_use_after_donate,
)
from torchmetrics_tpu.classification import BinaryAccuracy, BinaryF1Score
from torchmetrics_tpu.collections import MetricCollection

pytestmark = pytest.mark.lint


def _binary_batch():
    rng = np.random.default_rng(0)
    return (
        jnp.asarray(rng.random(32, dtype="float32")),
        jnp.asarray(rng.integers(0, 2, 32).astype("int32")),
    )


def _fused_group():
    """A jit compute-group collection after TWO updates — the second update
    is what aliases member states onto the group leader."""
    col = MetricCollection({"acc": BinaryAccuracy(), "f1": BinaryF1Score()}, jit=True)
    p, t = _binary_batch()
    col.update(p, t)
    col.update(p, t)
    return col


# ------------------------------------------------------------- live aliasing
def test_healthy_compute_group_is_clean():
    report = audit_donation(_fused_group())
    assert report.ok, report.issues
    assert report.alias_groups  # the aliasing itself is real and detected


def test_guard_removed_reproduces_aliased_donation():
    col = _fused_group()
    for _name, m in dict.items(col):  # raw access: bypass copy_state machinery
        m._state_shared = False
    report = audit_donation(col)
    assert not report.ok
    kinds = {i.kind for i in report.issues}
    assert kinds == {"aliased-donation"}
    # one finding per shared state leaf of the accuracy/f1 stat-scores group
    assert len(report.issues) == 5
    msg = report.issues[0].message
    assert "_state_shared" in msg and "donat" in msg


def test_single_metric_is_clean():
    m = BinaryAccuracy()
    m.update(*_binary_batch())
    assert audit_donation(m).ok


# ------------------------------------------------------------- donation mask
def test_donation_mask_consumed_leaves():
    mask = donation_mask(BinaryAccuracy(), "update", *_binary_batch())
    assert mask["entrypoint"] == "update"
    assert mask["donates"] is True
    assert mask["leaves"] == ("_n", "fn", "fp", "tn", "tp")
    assert mask["consumed"] == ("_n", "fn", "fp", "tn", "tp")


def test_donation_mask_respects_state_shared():
    m = BinaryAccuracy()
    m._state_shared = True
    mask = donation_mask(m, "update")
    assert mask["donates"] is False


# ------------------------------------------------------- AST use-after-donate
def test_package_has_no_use_after_donate():
    assert scan_use_after_donate() == []


def test_synthetic_use_after_donate_is_flagged(tmp_path):
    src = textwrap.dedent(
        """
        from torchmetrics_tpu.core.compile import compiled_update

        def step(metric, state, x):
            fn = compiled_update(metric, (x,), {})
            new = fn(state, x)
            total = state["total"]  # read of the donated buffer
            return new, total
        """
    )
    path = tmp_path / "bad_donate.py"
    path.write_text(src)
    issues = scan_use_after_donate(paths=[path], root=tmp_path)
    assert len(issues) == 1
    issue = issues[0]
    assert issue.kind == "use-after-donate"
    assert issue.line == 7  # the read, not the donating call
    assert "state" in issue.message


def test_same_unit_rebind_is_safe(tmp_path):
    src = textwrap.dedent(
        """
        from torchmetrics_tpu.core.compile import compiled_update

        def step(metric, state, x):
            fn = compiled_update(metric, (x,), {})
            state = fn(state, x)     # canonical donate-and-rebind
            return state["total"]    # reads the NEW buffer: fine
        """
    )
    path = tmp_path / "good_donate.py"
    path.write_text(src)
    assert scan_use_after_donate(paths=[path], root=tmp_path) == []


def test_donate_false_call_is_not_tracked(tmp_path):
    src = textwrap.dedent(
        """
        from torchmetrics_tpu.core.compile import compiled_update

        def step(metric, state, x):
            fn = compiled_update(metric, (x,), {}, donate=False)
            new = fn(state, x)
            return new, state["total"]  # buffer not donated: legal
        """
    )
    path = tmp_path / "nodonate.py"
    path.write_text(src)
    assert scan_use_after_donate(paths=[path], root=tmp_path) == []
