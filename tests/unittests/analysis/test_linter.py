"""Framework tests for the tier-1 AST linter: every registered rule fires on a
minimal synthetic offender, suppressions with justifications silence exactly
their rule, and the TMT009 hygiene rule polices the suppressions themselves.
"""

import textwrap

import pytest

from torchmetrics_tpu.analysis import all_rules, get_rule, lint_file, lint_paths
from torchmetrics_tpu.analysis.linter import Rule, parse_suppressions, register

pytestmark = pytest.mark.lint


def _lint(tmp_path, source, name="mod.py", select=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return lint_file(path, root=tmp_path, select=select)


def _ids(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------------ registry
def test_registry_is_complete_and_ordered():
    ids = [r.id for r in all_rules()]
    assert ids == sorted(ids)
    assert len(ids) >= 8
    assert get_rule("TMT001").name == "bare-print"


def test_register_rejects_bad_and_duplicate_ids():
    with pytest.raises(ValueError):

        @register
        class BadId(Rule):
            id = "TMT01X"
            name = "bad"
            description = "bad id format"

    with pytest.raises(ValueError):

        @register
        class Duplicate(Rule):
            id = "TMT001"
            name = "dupe"
            description = "already taken"


# ------------------------------------------------------------- rule triggers
def test_tmt001_bare_print(tmp_path):
    assert _ids(_lint(tmp_path, 'print("hi")\n')) == ["TMT001"]


def test_tmt002_direct_collective(tmp_path):
    src = """
    import jax

    def helper(x):
        return jax.lax.psum(x, "data")
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT002"]


def test_tmt002_allow_paths(tmp_path):
    src = 'import jax\n\ndef helper(x):\n    return jax.lax.psum(x, "data")\n'
    assert _lint(tmp_path, src, name="core/reductions.py") == []


def test_tmt003_host_sync_in_traced_fn(tmp_path):
    src = """
    def _update(self, state, x):
        bad = float(x)
        also_bad = x.item()
        fine = float(x.shape[0])
        return {"total": state["total"] + bad + also_bad + fine}
    """
    findings = _lint(tmp_path, src)
    assert _ids(findings) == ["TMT003", "TMT003"]
    assert {f.line for f in findings} == {3, 4}


def test_tmt003_jit_decorated_function(tmp_path):
    src = """
    import jax

    @jax.jit
    def step(x):
        return int(x)
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT003"]


def test_tmt004_traced_branch(tmp_path):
    src = """
    def _compute(self, state):
        if state["total"] > 0:
            return state["total"]
        return 0
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT004"]


def test_tmt004_structural_checks_allowed(tmp_path):
    src = """
    def _compute(self, state):
        if not state["preds"]:          # cat-state emptiness: tuple truthiness
            return 0
        if "extra" in state:            # dict membership
            return 1
        if state.get("x") is None:      # identity
            return 2
        return 3

    def _helper(iou, aggregate: bool = True):
        if not aggregate:               # constant-default config flag
            return iou
        return iou
    """
    assert _lint(tmp_path, src) == []


def test_tmt005_materialize_in_update(tmp_path):
    src = """
    import jax.numpy as jnp

    def _update(self, state, x):
        ones = jnp.array([1.0, 2.0])
        return {"total": state["total"] + x * ones}
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT005"]


def test_tmt006_wallclock_and_seedless_rng(tmp_path):
    src = """
    import time
    import numpy as np

    def helper():
        t0 = time.perf_counter()
        rng = np.random.default_rng()
        return t0, rng
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT006", "TMT006"]


def test_tmt006_seeded_rng_allowed(tmp_path):
    src = """
    import numpy as np

    def helper(seed):
        return np.random.default_rng(seed)
    """
    assert _lint(tmp_path, src) == []


def test_tmt007_state_mutation_outside_lifecycle(tmp_path):
    src = """
    class M:
        def reset(self):
            self._state = {}       # sanctioned

        def sneaky(self):
            self._state = {"x": 1}
            self._state["y"] = 2
    """
    findings = _lint(tmp_path, src)
    assert _ids(findings) == ["TMT007", "TMT007"]
    assert {f.line for f in findings} == {7, 8}


def test_tmt008_float64_literal(tmp_path):
    src = """
    import jax.numpy as jnp

    def helper(x):
        return x.astype(jnp.float64)
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT008"]


# ------------------------------------------------------------- suppressions
def test_suppression_with_justification_silences_rule(tmp_path):
    src = 'print("hi")  # tmt: ignore[TMT001] -- CLI banner at the host boundary\n'
    assert _lint(tmp_path, src) == []


def test_suppression_only_covers_named_rule(tmp_path):
    src = """
    def _update(self, state, x):
        return float(x)  # tmt: ignore[TMT005] -- wrong rule named on purpose
    """
    ids = _ids(_lint(tmp_path, src))
    assert "TMT003" in ids  # finding survives
    assert "TMT009" in ids  # and the suppression is reported stale


def test_suppression_without_justification_is_tmt009(tmp_path):
    src = 'print("hi")  # tmt: ignore[TMT001]\n'
    ids = _ids(_lint(tmp_path, src))
    assert ids == ["TMT009"]  # print suppressed, but hygiene flags the bare marker


def test_unknown_rule_id_is_tmt009(tmp_path):
    src = "x = 1  # tmt: ignore[TMT999] -- no such rule\n"
    findings = _lint(tmp_path, src)
    assert _ids(findings) == ["TMT009"]
    assert "unknown" in findings[0].message


def test_stale_suppression_is_tmt009(tmp_path):
    src = "x = 1  # tmt: ignore[TMT001] -- nothing to suppress here\n"
    findings = _lint(tmp_path, src)
    assert _ids(findings) == ["TMT009"]
    assert "stale" in findings[0].message


def test_marker_in_docstring_or_string_is_not_a_suppression():
    lines = [
        '"""Example: # tmt: ignore[TMT001] -- doc text."""',
        "MSG = 'write # tmt: ignore[TMT003] -- why'",
        "x = 1  # tmt: ignore[TMT001] -- a real comment",
    ]
    sups = parse_suppressions(lines)
    assert [s.line for s in sups] == [3]


# ------------------------------------------------------- select / multi-file
def test_select_runs_only_named_rules(tmp_path):
    src = """
    import jax.numpy as jnp

    def _update(self, state, x):
        y = jnp.array([1.0])
        return {"t": state["t"] + float(x) + y}
    """
    assert _ids(_lint(tmp_path, src, select=["TMT005"])) == ["TMT005"]


def test_select_disables_stale_detection(tmp_path):
    # under --select a suppression for a deselected rule must not look stale
    src = 'print("hi")  # tmt: ignore[TMT001] -- justified elsewhere\n'
    assert _lint(tmp_path, src, select=["TMT003"]) == []


def test_lint_paths_sorted_and_recursive(tmp_path):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "b.py").write_text('print("b")\n')
    (tmp_path / "pkg" / "a.py").write_text('print("a")\n')
    findings = lint_paths([tmp_path / "pkg"], root=tmp_path)
    assert [f.path for f in findings] == ["pkg/a.py", "pkg/b.py"]


# ----------------------------------------------- TMT004 match / walrus forms
def test_tmt004_match_on_traced_subject(tmp_path):
    src = """
    def _update(self, state, preds):
        match preds.sum():
            case 0:
                return state
            case _:
                return state
    """
    findings = _lint(tmp_path, src)
    assert _ids(findings) == ["TMT004"]
    assert "match" in findings[0].message


def test_tmt004_match_guard_on_traced_input(tmp_path):
    src = """
    def _update(self, state, preds, mode="sum"):
        match mode:
            case "sum" if preds.sum() > 0:
                return state
            case _:
                return state
    """
    findings = _lint(tmp_path, src)
    assert _ids(findings) == ["TMT004"]


def test_tmt004_match_on_config_is_allowed(tmp_path):
    src = """
    def _update(self, state, preds, mode="macro"):
        match mode:
            case "macro":
                return state
            case _:
                return state
    """
    assert _lint(tmp_path, src) == []


def test_tmt004_walrus_in_condition(tmp_path):
    src = """
    def _update(self, state, preds):
        if (total := preds.sum()) > 0:
            return {"t": state["t"] + total}
        return state
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT004"]


def test_tmt004_walrus_taint_propagates_to_later_branch(tmp_path):
    src = """
    def _update(self, state, preds):
        y = (s := preds.sum())
        if s > 0:
            return {"t": state["t"] + y}
        return state
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT004"]


# ------------------------------------------- TMT009 satellite: multi-rule &
# decorated-nested-function suppressions
def test_multi_rule_one_line_suppression(tmp_path):
    src = """
    import jax.numpy as jnp

    def _update(self, state, x):
        return {"t": state["t"] + float(x) + jnp.array([1.0])}  # tmt: ignore[TMT003,TMT005] -- host fallback path, constant folded once
    """
    assert _lint(tmp_path, src) == []


def test_multi_rule_suppression_partially_stale_is_tmt009(tmp_path):
    src = """
    def _update(self, state, x):
        return {"t": state["t"] + float(x)}  # tmt: ignore[TMT003,TMT005] -- only TMT003 actually fires here
    """
    ids = _ids(_lint(tmp_path, src))
    assert ids == ["TMT009"]  # TMT003 suppressed; TMT005 half reported stale


def test_suppression_in_decorated_nested_function(tmp_path):
    src = """
    import functools

    def make_update(scale):
        @functools.lru_cache(maxsize=1)
        def _update(self, state, x):
            return {"t": state["t"] + float(x) * scale}  # tmt: ignore[TMT003] -- eager-only helper, never jitted
        return _update
    """
    assert _lint(tmp_path, src) == []


def test_stale_suppression_in_decorated_nested_function_is_tmt009(tmp_path):
    src = """
    import functools

    def make_update(scale):
        @functools.lru_cache(maxsize=1)
        def _update(self, state, x):
            return {"t": state["t"] * scale}  # tmt: ignore[TMT003] -- nothing fires on this line
        return _update
    """
    assert _ids(_lint(tmp_path, src)) == ["TMT009"]


def test_whole_program_rules_registered_and_inert_per_file(tmp_path):
    # TMT010-013 live in the registry (so --select and suppressions know
    # them) but never produce per-file findings from lint_file
    ids = [r.id for r in all_rules()]
    for rid in ("TMT010", "TMT011", "TMT012", "TMT013"):
        assert rid in ids
        assert get_rule(rid).whole_program
    src = 'x = 1  # tmt: ignore[TMT011] -- whole-program suppression, never stale per-file\n'
    assert _lint(tmp_path, src) == []


def test_tmt018_suppression_recognized_and_never_stale(tmp_path):
    # tier-5 batchability ids are whole-program: a suppression naming them is
    # known to TMT009 (not "unknown rule") and exempt from stale detection
    assert get_rule("TMT018").whole_program
    src = 'x = 1  # tmt: ignore[TMT018] -- host-side compute by design; certificate classifies it\n'
    assert _lint(tmp_path, src) == []


def test_tmt019_suppression_recognized_and_never_stale(tmp_path):
    assert get_rule("TMT019").whole_program
    src = 'x = 1  # tmt: ignore[TMT019] -- cross-tenant mixing is the point of this aggregate\n'
    assert _lint(tmp_path, src) == []


def test_tmt020_suppression_recognized_and_never_stale(tmp_path):
    assert get_rule("TMT020").whole_program
    src = 'x = 1  # tmt: ignore[TMT020] -- eviction handled via stashed init constants\n'
    assert _lint(tmp_path, src) == []


def test_tmt021_suppression_recognized_and_never_stale(tmp_path):
    assert get_rule("TMT021").whole_program
    src = 'x = 1  # tmt: ignore[TMT021] -- padding handled by explicit masking, not identity rows\n'
    assert _lint(tmp_path, src) == []
