"""Tier-2 contract auditor tests: clean metrics pass every check, the
planner's collective count matches the lowered sync jaxpr (the Acc+F1+AUROC
12-leaf -> 2-bucket case), and metrics that smuggle host callbacks or
unregistered state leaves into the trace are rejected.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.aggregation import CatMetric, MeanMetric
from torchmetrics_tpu.analysis import TraceContractError, audit_collection, audit_metric
from torchmetrics_tpu.analysis.audit import COLLECTIVE_PRIMITIVES, count_primitives, iter_eqns
from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.parallel.coalesce import per_leaf_collective_count, plan_for_metrics


@pytest.fixture
def clf_batch():
    rng = np.random.default_rng(7)
    preds = jnp.asarray(rng.standard_normal((32, 5)), jnp.float32)
    target = jnp.asarray(rng.integers(0, 5, 32))
    return preds, target


# ------------------------------------------------------------- clean metrics
def test_accuracy_passes_all_checks(clf_batch):
    rep = audit_metric(MulticlassAccuracy(num_classes=5, average="micro"), *clf_batch)
    assert rep.ok, rep.violations
    assert set(rep.checks) == {
        "state-registration",
        "update",
        "compute",
        "sync-collective-count",
        "ragged-gather",
    }
    assert rep.skipped == ()
    assert rep.traced_sync_collectives == rep.planned_sync_collectives
    assert rep.traced_sync_gathers == 0  # all-sum state: nothing to gather


def test_mean_metric_passes(clf_batch):
    rep = audit_metric(MeanMetric(), jnp.abs(clf_batch[0][:, 0]))
    assert rep.ok, rep.violations
    assert rep.traced_sync_collectives == rep.planned_sync_collectives


def test_cat_state_metric_passes():
    rep = audit_metric(CatMetric(), jnp.arange(8, dtype=jnp.float32))
    assert rep.ok, rep.violations
    # cat leaves pass through the plan as individual all_gathers; the traced
    # graph must still match the planner's model exactly
    assert rep.traced_sync_collectives == rep.planned_sync_collectives


def test_string_input_text_metric_skips_update_trace():
    from torchmetrics_tpu.text.asr import WordErrorRate

    rep = audit_metric(WordErrorRate(), ["hello world"], ["hello there world"])
    assert rep.ok, rep.violations
    assert "state-registration" in rep.checks  # eager update still audited
    assert any(check == "update" for check, _ in rep.skipped)


# ------------------------------------------------- planner vs lowered graph
def test_collection_sync_matches_plan_12_to_2(clf_batch):
    col = MetricCollection(
        MulticlassAccuracy(num_classes=5, average="micro"),
        MulticlassF1Score(num_classes=5, average="macro"),
        MulticlassAUROC(num_classes=5, thresholds=16),
        compute_groups=True,
    )
    rep = audit_collection(col, *clf_batch)
    assert rep.ok, rep.violations
    assert rep.traced_sync_collectives == rep.planned_sync_collectives
    assert rep.traced_sync_collectives <= 2

    # and the fusion is real: per-leaf the same leaders would need >= 12
    leaders = [col[m[0]] for m in col._functional_groups().values()]
    states = [m.update_state(m.init_state(), *clf_batch) for m in leaders]
    per_leaf = sum(per_leaf_collective_count(m._reductions, s) for m, s in zip(leaders, states))
    assert per_leaf >= 12
    plan, _ = plan_for_metrics(leaders, states)
    assert plan.n_collectives == rep.planned_sync_collectives


def test_jaxpr_walker_counts_nested_collectives():
    jx = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones(3))
    assert count_primitives(jx, COLLECTIVE_PRIMITIVES) == 0
    assert any(e.primitive.name == "mul" for e in iter_eqns(jx))


# ------------------------------------------------------------ broken metrics
class _CallbackInUpdate(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        peek = jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((), jnp.float32), x.sum()
        )
        return {"total": state["total"] + peek}

    def _compute(self, state):
        return state["total"]


class _UnregisteredLeaf(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        return {"total": state["total"] + x.sum(), "rogue": x.mean()}

    def _compute(self, state):
        return state["total"]


def test_host_callback_in_update_is_rejected():
    rep = audit_metric(_CallbackInUpdate(), jnp.ones(4, jnp.float32))
    assert not rep.ok
    assert any(v.check == "update" and "pure_callback" in v.message for v in rep.violations)


def test_strict_mode_raises_with_report_attached():
    with pytest.raises(TraceContractError) as err:
        audit_metric(_CallbackInUpdate(), jnp.ones(4, jnp.float32), strict=True)
    assert not err.value.report.ok
    assert "pure_callback" in str(err.value)


def test_unregistered_state_leaf_is_rejected():
    rep = audit_metric(_UnregisteredLeaf(), jnp.ones(4, jnp.float32))
    assert not rep.ok
    assert any(v.check == "state-registration" and "rogue" in v.message for v in rep.violations)


def test_report_round_trips_to_dict(clf_batch):
    rep = audit_metric(MulticlassAccuracy(num_classes=5, average="micro"), *clf_batch)
    d = rep.as_dict()
    assert d["ok"] is True
    assert d["traced_sync_collectives"] == d["planned_sync_collectives"]
    assert "sync-collective-count" in d["checks"]


# ------------------------------------------------------------ compressed sync
def test_audit_compressed_sync_contract(clf_batch):
    """Satellite: auditing with a compression config proves the quantized
    sync lowers exactly the planner's collective count, keeps host callbacks
    out of the trace, and confines dequantize ops to the sync graph — the
    update trace stays dequantize-free."""
    # MSE with many outputs: `measure` is a float32 sum leaf big enough to
    # clear the bucket-size floor (the confusion/stat counters are int32 now
    # and integer buckets never compress)
    from torchmetrics_tpu.parallel.compress import CompressionConfig
    from torchmetrics_tpu.regression import MeanSquaredError

    rng = np.random.default_rng(21)
    preds = jnp.asarray(rng.normal(size=(32, 2048)), jnp.float32)
    target = jnp.asarray(rng.normal(size=(32, 2048)), jnp.float32)
    m = MeanSquaredError(num_outputs=2048)
    rep = audit_metric(m, preds, target, compression=CompressionConfig("int8", 0.05))
    assert rep.ok, rep.violations
    comp = rep.compression
    assert comp is not None
    assert comp["mode"] == "int8"
    assert comp["compressed_buckets"] >= 1
    assert comp["traced_collectives"] == comp["planned_collectives"]
    assert comp["dequantize_in_sync"] >= 1
    assert comp["dequantize_in_update"] == 0
    assert "compression" in rep.as_dict()


def test_audit_without_compression_reports_none(clf_batch):
    rep = audit_metric(MulticlassAccuracy(num_classes=5, average="micro"), *clf_batch)
    assert rep.compression is None
    assert rep.as_dict()["compression"] is None


def test_count_dequantize_ops_walker():
    from torchmetrics_tpu.analysis.audit import count_dequantize_ops

    def quantish(x):
        q = x.astype(jnp.bfloat16).astype(jnp.float32)  # one wire->f32 widen
        return q + x.astype(jnp.int8).astype(jnp.float32)  # and another

    jx = jax.make_jaxpr(quantish)(jnp.ones((8,), jnp.float32))
    assert count_dequantize_ops(jx) == 2
    jx_plain = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((8,), jnp.float32))
    assert count_dequantize_ops(jx_plain) == 0
