"""TMT011 fingerprint-completeness checker.

Stale-trace bug class: an attribute that influences traced code but is
invisible to ``config_fingerprint`` lets two differently-configured
instances share one compile-cache entry.  The checker's attribute dataflow
must catch synthetic offenders, pass clean derived-attribute patterns, and
— via the dynamic ``fingerprint_insensitive`` cross-check — agree with what
``explain_retrace`` would observe.
"""

import importlib.util
import sys
import textwrap

import pytest

from torchmetrics_tpu.analysis.fingerprint import (
    check_class_fingerprint,
    check_fingerprint,
    fingerprint_insensitive,
    scan_package_fingerprints,
)
from torchmetrics_tpu.analysis.linter import apply_suppressions
from torchmetrics_tpu.analysis.sanitizer import run_fingerprint_pass

pytestmark = pytest.mark.lint

_FIXTURE_SRC = textwrap.dedent(
    """
    import jax.numpy as jnp
    from torchmetrics_tpu.core.metric import Metric


    class BadScale(Metric):
        '''Private attr fed by an unmirrored ctor param: classic offender.'''

        def __init__(self, scale=2.0, **kw):
            super().__init__(**kw)
            self._scale = scale
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def _update(self, state, x):
            return {"total": state["total"] + self._scale * x.sum()}

        def _compute(self, state):
            return state["total"]


    class ExcludedRead(Metric):
        '''Public attr read in trace but opted out of the fingerprint.'''

        __fingerprint_exclude__ = ("mode",)

        def __init__(self, mode="a", **kw):
            super().__init__(**kw)
            self.mode = mode
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def _update(self, state, x):
            s = x.sum() if self.mode == "a" else x.max()
            return {"total": state["total"] + s}

        def _compute(self, state):
            return state["total"]


    class GoodScale(Metric):
        '''Private attrs derived from mirrored/public config: safe.'''

        def __init__(self, scale=2.0, **kw):
            super().__init__(**kw)
            self.scale = scale
            self._scale2 = float(scale) * 2
            self._table = {k: k * self._scale2 for k in range(3)}
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def _update(self, state, x):
            return {"total": state["total"] + self._scale2 * x.sum() + self._table[1]}

        def _compute(self, state):
            return state["total"]


    class MutatedInTrace(Metric):
        '''Private attr reassigned outside the construction lifecycle.'''

        def __init__(self, **kw):
            super().__init__(**kw)
            self._bias = 0.0
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def set_bias(self, b):
            self._bias = b

        def _update(self, state, x):
            return {"total": state["total"] + x.sum() + self._bias}

        def _compute(self, state):
            return state["total"]
    """
)


@pytest.fixture(scope="module")
def fixture_mod(tmp_path_factory):
    path = tmp_path_factory.mktemp("fp") / "fp_fixture_metrics.py"
    path.write_text(_FIXTURE_SRC)
    spec = importlib.util.spec_from_file_location("fp_fixture_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    yield mod
    sys.modules.pop(spec.name, None)


def test_unmirrored_private_param_is_flagged(fixture_mod):
    issues = check_class_fingerprint(fixture_mod.BadScale)
    assert [(i.attr, i.kind) for i in issues] == [("_scale", "unfingerprinted-private")]
    assert "compile-cache key" in issues[0].message


def test_excluded_public_read_is_flagged(fixture_mod):
    issues = check_class_fingerprint(fixture_mod.ExcludedRead)
    assert [(i.attr, i.kind) for i in issues] == [("mode", "excluded-read")]


def test_derived_private_attrs_are_safe(fixture_mod):
    assert check_class_fingerprint(fixture_mod.GoodScale) == []


def test_mutation_outside_lifecycle_is_flagged(fixture_mod):
    issues = check_class_fingerprint(fixture_mod.MutatedInTrace)
    assert [(i.attr, i.kind) for i in issues] == [("_bias", "mutated-in-trace")]


def test_instance_check_filters_to_carried_attrs(fixture_mod):
    m = fixture_mod.BadScale()
    assert [i.attr for i in check_fingerprint(m)] == ["_scale"]


def test_dynamic_cross_check_confirms_findings(fixture_mod):
    # mutating the flagged attr moves nothing in the fingerprint — i.e.
    # explain_retrace would attribute NO retrace to it: the hazard is real
    assert fingerprint_insensitive(fixture_mod.BadScale(), "_scale")
    # while a fingerprinted public attr IS sensitive
    assert not fingerprint_insensitive(fixture_mod.GoodScale(), "scale")


# ----------------------------------------------------------- package dogfood
def test_package_scan_only_suppressed_findings():
    # the raw scan may surface statically-unprovable-but-justified sites;
    # each must carry a # tmt: ignore[TMT011] at its read line
    assert apply_suppressions(run_fingerprint_pass()) == []


def test_fbeta_beta_is_fingerprinted():
    # regression: beta was a private-only attr — two FBeta instances
    # differing only in beta shared one compile-cache key
    from torchmetrics_tpu.classification import BinaryFBetaScore

    a, b = BinaryFBetaScore(beta=0.5), BinaryFBetaScore(beta=2.0)
    assert a._config_fingerprint() != b._config_fingerprint()


def test_psnr_clamp_bounds_are_fingerprinted():
    # regression: data_range=(0, 1) vs (1, 2) share data_range == 1.0 but
    # compile different clip constants — the bounds must key the cache
    from torchmetrics_tpu.image import PeakSignalNoiseRatio

    a = PeakSignalNoiseRatio(data_range=(0.0, 1.0))
    b = PeakSignalNoiseRatio(data_range=(1.0, 2.0))
    assert a._config_fingerprint() != b._config_fingerprint()


def test_scan_package_returns_only_known_justified_sites():
    issues = scan_package_fingerprints()
    assert {(i.cls, i.attr) for i in issues} <= {("BERTScore", "_zero_special")}
