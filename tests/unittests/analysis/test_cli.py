"""CLI contract: ``python -m torchmetrics_tpu.analysis`` is the CI gate.
Exit 0 + parseable JSON over the installed package is a tier-1 invariant —
a regression here is a lint failure in disguise.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def _run(*args):
    return subprocess.run(
        [sys.executable, "-m", "torchmetrics_tpu.analysis", *args],
        capture_output=True,
        text=True,
        env=_ENV,
        timeout=120,
    )


def test_package_is_clean_json():
    proc = _run("--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["n_findings"] == 0
    assert report["findings"] == []
    assert len(report["rules"]) >= 8


def test_findings_exit_code_is_one(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text('print("hi")\n')
    proc = _run(str(bad), "--format", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["n_findings"] == 1
    assert report["findings"][0]["rule"] == "TMT001"


def test_unknown_select_is_usage_error():
    proc = _run("--select", "TMT999")
    assert proc.returncode == 2
    assert "unknown rule id" in proc.stderr


def test_list_rules_prints_registry():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for rid in ("TMT001", "TMT002", "TMT003", "TMT009"):
        assert rid in proc.stdout
