"""CLI contract: ``python -m torchmetrics_tpu.analysis`` is the CI gate.
Exit 0 + parseable JSON over the installed package is a tier-1 invariant —
a regression here is a lint failure in disguise.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def _run(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "torchmetrics_tpu.analysis", *args],
        capture_output=True,
        text=True,
        env=_ENV,
        timeout=timeout,
    )


def test_package_is_clean_json():
    proc = _run("--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["n_findings"] == 0
    assert report["findings"] == []
    assert len(report["rules"]) >= 8


def test_findings_exit_code_is_one(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text('print("hi")\n')
    proc = _run(str(bad), "--format", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["n_findings"] == 1
    assert report["findings"][0]["rule"] == "TMT001"


def test_unknown_select_is_usage_error():
    proc = _run("--select", "TMT999")
    assert proc.returncode == 2
    assert "unknown rule id" in proc.stderr


def test_list_rules_prints_registry():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for rid in ("TMT001", "TMT002", "TMT003", "TMT009"):
        assert rid in proc.stdout


def test_list_rules_tags_whole_program_passes():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for rid in (
        "TMT010", "TMT011", "TMT012", "TMT013", "TMT014", "TMT015", "TMT016", "TMT017",
        "TMT018", "TMT019", "TMT020", "TMT021",
    ):
        line = next(l for l in proc.stdout.splitlines() if l.startswith(rid))
        assert "[whole-program]" in line


@pytest.mark.contracts
def test_horizons_prints_saturation_table():
    proc = _run("--horizons")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "horizon (samples)" in proc.stdout
    # the two documented float/int accumulators appear with their kinds
    assert "MeanMetric" in proc.stdout and "stagnation" in proc.stdout
    assert "PeakSignalNoiseRatio" in proc.stdout and "saturation" in proc.stdout


@pytest.mark.contracts
def test_horizons_flags_change_the_rendered_assumptions():
    proc = _run("--horizons", "--batch-size", "1024", "--sample-budget", "1e6")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "updates@1024" in proc.stdout


def test_github_format_emits_error_annotations(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text('print("hi")\n')
    proc = _run(str(bad), "--format", "github")
    assert proc.returncode == 1
    lines = proc.stdout.splitlines()
    assert lines[0].startswith("::error file=")
    assert "line=1" in lines[0] and "title=TMT001" in lines[0]
    assert lines[-1].endswith("1 finding(s)")


def test_parse_error_exit_two_names_failing_file(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    proc = _run(str(broken))
    assert proc.returncode == 2
    assert "parse error in" in proc.stderr
    assert "broken.py" in proc.stderr


def test_missing_path_is_usage_error(tmp_path):
    proc = _run(str(tmp_path / "nope.py"))
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


@pytest.mark.batchability
def test_certify_fleet_exit_code_contract():
    """0 when the slate matches the golden certificate; 1 on drift, with a
    primitive/verdict-level diff rendered as findings (github annotations
    included); the golden file is restored afterwards."""
    from torchmetrics_tpu.analysis.batchability import certificate_path

    path = certificate_path()
    assert path.is_file(), "golden FleetCertificate.json missing"
    golden_text = path.read_text()

    proc = _run("--certify-fleet", "--format", "json", timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["n_findings"] == 0

    tampered = json.loads(golden_text)
    name = tampered["eligible"]["direct"][0]
    tampered["metrics"][name]["verdict"] = "unliftable"
    try:
        path.write_text(json.dumps(tampered, indent=2, sort_keys=True) + "\n")
        proc = _run("--certify-fleet", "--format", "github", timeout=240)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "::error file=" in proc.stdout
        assert "title=TMT018" in proc.stdout
        assert "verdict changed" in proc.stdout
    finally:
        path.write_text(golden_text)


@pytest.mark.contracts
def test_audit_all_is_clean_and_within_budget():
    import time

    t0 = time.monotonic()
    proc = _run("--audit-all", "--format", "json")
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["n_findings"] == 0
    assert wall < 60.0  # generous CI ceiling; bench.py enforces the 20s budget
