"""Tier-5 batchability certifier tests (TMT018–TMT021).

Each seeded-broken metric below violates exactly one reason code; the
certifier must reject every one of them (no false negatives), and the
runtime cross-check must confirm sampled ``liftable`` verdicts by actual
vmap-stacked parity against a Python loop (no false positives).
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from torchmetrics_tpu.analysis.batchability import (
    BATCHABILITY_RULE_IDS,
    CERTIFICATE_SCHEMA_VERSION,
    certificate_path,
    certify_live,
    certify_metric,
    diff_certificate,
    fleet_slate,
    runtime_crosscheck,
    tenant_flow,
)
from torchmetrics_tpu.core.metric import Metric
from torchmetrics_tpu.core.reductions import Reduce, SketchReduce, reduce_identity

pytestmark = pytest.mark.batchability


def _x(n: int = 16):
    return (jnp.linspace(0.0, 1.0, n, dtype=jnp.float32),)


def _codes(cert):
    return {(r.rule, r.code) for r in cert.reasons}


# ------------------------------------------------------------ reduce_identity
def test_reduce_identity_elementwise_families():
    assert float(reduce_identity(Reduce.SUM, jnp.float32)) == 0.0
    assert float(reduce_identity(Reduce.MEAN, jnp.float32)) == 0.0
    assert float(reduce_identity(Reduce.MAX, jnp.float32)) == float("-inf")
    assert float(reduce_identity(Reduce.MIN, jnp.float32)) == float("inf")
    # integer leaves narrow to the iinfo bound — that bound IS absorbing
    assert int(reduce_identity(Reduce.MAX, jnp.int32)) == jnp.iinfo(jnp.int32).min
    assert int(reduce_identity(Reduce.MIN, jnp.int32)) == jnp.iinfo(jnp.int32).max
    assert bool(reduce_identity(Reduce.MAX, jnp.bool_)) is False
    assert bool(reduce_identity(Reduce.MIN, jnp.bool_)) is True


def test_reduce_identity_has_none_for_unmergeable_families():
    # CAT concatenates, NONE concatenates under merge_leaf, structural
    # sketches and callables have no elementwise algebra at all
    assert reduce_identity(Reduce.CAT, jnp.float32) is None
    assert reduce_identity(Reduce.NONE, jnp.float32) is None
    assert reduce_identity(lambda s: s[0], jnp.float32) is None
    structural = SketchReduce("t", bucket_op=None, combine_stacked=lambda s: s[0])
    assert reduce_identity(structural, jnp.float32) is None
    summing = SketchReduce("t", bucket_op="sum", combine_stacked=jnp.sum)
    assert float(reduce_identity(summing, jnp.float32)) == 0.0


# ----------------------------------------------- TMT018: seeded-broken lifts
class _CatState(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("values", [], dist_reduce_fx="cat")

    def _update(self, state, x):
        return {"values": state["values"] + (x,)}

    def _compute(self, state):
        return jnp.concatenate(state["values"]).mean()


def test_tmt018_cat_state_rejected():
    cert = certify_live("CatState", _CatState(), _x())
    assert cert.verdict == "unliftable"
    assert ("TMT018", "cat-state") in _codes(cert)


class _Callback(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        import numpy as np

        host_sum = jax.pure_callback(
            lambda a: np.sum(a, dtype=np.float32),
            jax.ShapeDtypeStruct((), jnp.float32),
            x,
            vmap_method="sequential",
        )
        return {"total": state["total"] + host_sum}

    def _compute(self, state):
        return state["total"]


def test_tmt018_pure_callback_rejected():
    cert = certify_live("Callback", _Callback(), _x())
    assert cert.verdict == "unliftable"
    assert ("TMT018", "pure-callback") in _codes(cert)


class _MaskIndex(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        kept = x[x > 0.5]  # data-dependent output shape
        return {"total": state["total"] + kept.sum()}

    def _compute(self, state):
        return state["total"]


def test_tmt018_data_dependent_shape_rejected():
    cert = certify_live("MaskIndex", _MaskIndex(), _x())
    assert cert.verdict == "unliftable"
    assert ("TMT018", "data-dependent-shape") in _codes(cert)


class _Branch(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        if x.sum() > 0:  # Python branch on tenant data
            return {"total": state["total"] + x.sum()}
        return {"total": state["total"]}

    def _compute(self, state):
        return state["total"]


def test_tmt018_traced_branch_rejected():
    cert = certify_live("Branch", _Branch(), _x())
    assert cert.verdict == "unliftable"
    assert ("TMT018", "traced-branch") in _codes(cert)


# --------------------------------------------- TMT019: tenant independence
class _AliasedLeaves(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("a", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("b", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        s = state["a"] + x.sum()
        return {"a": s, "b": s}  # one buffer serving two leaves

    def _compute(self, state):
        return state["a"] + state["b"]


def test_tmt019_aliased_state_leaves_rejected():
    cert = certify_live("AliasedLeaves", _AliasedLeaves(), _x())
    assert cert.verdict == "unliftable"
    assert ("TMT019", "aliased-state-leaves") in _codes(cert)


def test_tenant_flow_flags_cross_tenant_reduction():
    # a stacked-level graph that sums over the tenant axis — exactly what a
    # buggy fleet aggregation would lower
    jx = jax.make_jaxpr(lambda s: jnp.sum(s, axis=0))(jnp.zeros((3, 8)))
    status, problems = tenant_flow(jx)
    assert any("reduces over the tenant axis" in p for p in problems)


def test_tenant_flow_tracks_clean_per_tenant_graph():
    jx = jax.make_jaxpr(lambda s, x: s + x.sum(axis=1, keepdims=False))(
        jnp.zeros((3,)), jnp.zeros((3, 8))
    )
    status, problems = tenant_flow(jx)
    assert status == "tracked"
    assert problems == []


def test_tenant_flow_flags_moved_output_axis():
    jx = jax.make_jaxpr(lambda s: jnp.transpose(s))(jnp.zeros((3, 8)))
    status, problems = tenant_flow(jx)
    assert any("tenant axis at dim" in p for p in problems)


# ------------------------------------------------- TMT020: reset soundness
class _BadReset(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        # max-reduced leaf seeded at 0: merge(state, init) clamps at 0, and
        # evicting a tenant by writing the identity (-inf) is NOT init state
        self.add_state("peak", jnp.zeros(()), dist_reduce_fx="max")

    def _update(self, state, x):
        return {"peak": jnp.maximum(state["peak"], x.max())}

    def _compute(self, state):
        return state["peak"]


def test_tmt020_reset_not_identity_demotes_to_masking():
    cert = certify_live("BadReset", _BadReset(), _x(), check_sync=False)
    assert cert.verdict == "liftable-with-masking"
    assert ("TMT020", "reset-not-identity") in _codes(cert)
    assert cert.leaves["peak"]["reset"] == "init-constant"


def test_tmt020_identity_reset_stays_liftable():
    class _GoodReset(_BadReset):
        def __init__(self, **kw):
            Metric.__init__(self, **kw)
            self.add_state("peak", jnp.full((), -jnp.inf), dist_reduce_fx="max")

    cert = certify_live("GoodReset", _GoodReset(), _x(), check_sync=False)
    assert cert.verdict == "liftable"
    assert cert.leaves["peak"]["reset"] == "identity"


# ----------------------------------------------- TMT021: padding soundness
class _ClippedIdentity(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        # MIN identity is +inf, but the declared range tops out at 1.0: an
        # identity-padded row would violate the range contract
        self.add_state("low", jnp.ones(()), dist_reduce_fx="min", value_range=(0.0, 1.0))

    def _update(self, state, x):
        return {"low": jnp.minimum(state["low"], x.min())}

    def _compute(self, state):
        return state["low"]


def test_tmt021_identity_out_of_range_demotes_to_masking():
    cert = certify_live("ClippedIdentity", _ClippedIdentity(), _x(), check_sync=False)
    assert cert.verdict == "liftable-with-masking"
    assert ("TMT021", "identity-out-of-range") in _codes(cert)


class _PerturbingMerge(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state, x):
        return {"total": state["total"] + x.sum()}

    def _compute(self, state):
        return state["total"]

    def merge_states(self, a, b):
        merged = super().merge_states(a, b)
        merged["total"] = merged["total"] + 1.0  # identity rows do not absorb
        return merged


def test_tmt021_padding_perturbs_state_rejected():
    cert = certify_live("PerturbingMerge", _PerturbingMerge(), _x(), check_sync=False)
    assert cert.verdict == "unliftable"
    assert ("TMT021", "padding-perturbs-state") in _codes(cert)


def test_tmt021_no_identity_on_none_reduced_array_leaf():
    # RunningSum's ring buffer is dist_reduce_fx=None: merge_leaf
    # concatenates it, so there is no absorbing identity and no certificate
    cert = certify_metric("RunningSum", fleet_slate()["RunningSum"])
    assert cert.verdict == "unliftable"
    assert ("TMT021", "no-identity") in _codes(cert)


# ------------------------------------------------------- the certificate
def _golden() -> dict:
    path = certificate_path()
    assert path.is_file(), "golden FleetCertificate.json missing — run --certify-fleet --update-contracts"
    return json.loads(path.read_text())


def test_golden_certificate_schema_and_consistency():
    doc = _golden()
    assert doc["schema"] == CERTIFICATE_SCHEMA_VERSION
    assert doc["certifier"] == "tm-tpu-fleet-cert/1"
    metrics = doc["metrics"]
    assert doc["summary"]["total"] == len(metrics) >= 200
    # eligibility lists are exactly the verdict partitions
    assert doc["eligible"]["direct"] == sorted(
        n for n, e in metrics.items() if e["verdict"] == "liftable"
    )
    assert doc["eligible"]["masked"] == sorted(
        n for n, e in metrics.items() if e["verdict"] == "liftable-with-masking"
    )
    assert len(doc["eligible"]["direct"]) >= 80
    # no internal certifier errors anywhere in the slate
    assert not [
        n for n, e in metrics.items() if any(r["code"] == "certifier-error" for r in e["reasons"])
    ]
    # every non-liftable verdict carries at least one structured reason
    for name, entry in metrics.items():
        if entry["verdict"] != "liftable":
            assert entry["reasons"], name
        for reason in entry["reasons"]:
            assert reason["rule"] in BATCHABILITY_RULE_IDS


def test_certificate_diff_is_reflexive_and_detects_drift():
    doc = _golden()
    assert diff_certificate(doc, doc) == []
    tampered = json.loads(json.dumps(doc))
    name = doc["eligible"]["direct"][0]
    tampered["metrics"][name]["verdict"] = "unliftable"
    tampered["metrics"][name]["evidence"]["update_primitives"]["add"] = 999
    diffs = diff_certificate(doc, tampered)
    assert any("verdict changed" in d for d in diffs)
    assert any("primitive 'add'" in d for d in diffs)


def test_golden_certificate_names_known_classifications():
    doc = _golden()
    m = doc["metrics"]
    # the dogfooded classifications this PR surfaced, pinned
    assert m["PeakSignalNoiseRatioWithBlockedEffect"]["verdict"] == "liftable-with-masking"
    assert m["PearsonCorrCoef"]["verdict"] == "liftable-with-masking"
    assert m["RunningMean"]["verdict"] == "unliftable"
    assert m["BinaryAccuracy"]["verdict"] == "liftable"
    assert m["MeanSquaredError"]["verdict"] == "liftable"
    assert m["CatMetric"]["verdict"] == "unliftable"
    assert m["FrechetInceptionDistance"]["verdict"] == "unevaluated"


# --------------------------------------------------- runtime cross-check
def test_runtime_crosscheck_confirms_sampled_liftable_verdicts():
    checked, problems = runtime_crosscheck(_golden(), sample_size=6)
    assert problems == []
    assert len(checked) == 6


def test_runtime_crosscheck_spreads_the_sample():
    doc = _golden()
    checked, _ = runtime_crosscheck(doc, sample_size=4)
    # deterministic spread across the liftable list, not a prefix
    liftable = doc["eligible"]["direct"]
    assert checked[0] == liftable[0]
    assert checked[-1] != liftable[3]
