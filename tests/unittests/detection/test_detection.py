"""Detection metric tests.

mAP oracles: the reference's doctest output (detection/mean_ap.py:230-276) and
hand-derived COCO 101-point interpolation cases.  Panoptic oracles: reference
doctest values (functional/detection/panoptic_qualities.py).
"""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from torchmetrics_tpu.detection import (
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
    MeanAveragePrecision,
    ModifiedPanopticQuality,
    PanopticQuality,
)
from torchmetrics_tpu.functional.detection import (
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
    modified_panoptic_quality,
    panoptic_quality,
)


# ------------------------------------------------------------------ box IoU
def test_iou_functional_basic():
    a = jnp.asarray([[0.0, 0.0, 10.0, 10.0]])
    b = jnp.asarray([[5.0, 5.0, 15.0, 15.0]])
    got = float(intersection_over_union(a, b, aggregate=False)[0, 0])
    assert got == pytest.approx(25.0 / 175.0, abs=1e-6)
    # GIoU of identical boxes = 1; far-apart boxes < 0
    assert float(generalized_intersection_over_union(a, a, aggregate=False)[0, 0]) == pytest.approx(1.0)
    far = jnp.asarray([[100.0, 100.0, 110.0, 110.0]])
    assert float(generalized_intersection_over_union(a, far, aggregate=False)[0, 0]) < 0
    assert float(distance_intersection_over_union(a, far, aggregate=False)[0, 0]) < 0
    assert float(complete_intersection_over_union(a, a, aggregate=False)[0, 0]) == pytest.approx(1.0, abs=1e-5)


def test_iou_class_oracle():
    preds = [{
        "boxes": jnp.asarray([[296.55, 93.96, 314.97, 152.79], [298.55, 98.96, 314.97, 151.79]]),
        "labels": jnp.asarray([4, 5]),
    }]
    target = [{
        "boxes": jnp.asarray([[300.00, 100.00, 315.00, 150.00]]),
        "labels": jnp.asarray([5]),
    }]
    m = IntersectionOverUnion()
    m.update(preds, target)
    res = m.compute()
    assert float(res["iou"]) == pytest.approx(0.8614, abs=1e-4)


def test_iou_class_respect_labels_false():
    preds = [{
        "boxes": jnp.asarray([[0.0, 0.0, 10.0, 10.0]]),
        "labels": jnp.asarray([1]),
    }]
    target = [{
        "boxes": jnp.asarray([[0.0, 0.0, 10.0, 10.0]]),
        "labels": jnp.asarray([2]),
    }]
    m1 = IntersectionOverUnion(respect_labels=True)
    m1.update(preds, target)
    assert float(m1.compute()["iou"]) == 0.0  # nothing valid
    m2 = IntersectionOverUnion(respect_labels=False)
    m2.update(preds, target)
    assert float(m2.compute()["iou"]) == pytest.approx(1.0)


@pytest.mark.parametrize(
    "cls", [GeneralizedIntersectionOverUnion, DistanceIntersectionOverUnion, CompleteIntersectionOverUnion]
)
def test_iou_variants_classes_run(cls):
    preds = [{
        "boxes": jnp.asarray([[0.0, 0.0, 10.0, 10.0], [20.0, 20.0, 30.0, 30.0]]),
        "labels": jnp.asarray([0, 0]),
    }]
    target = [{
        "boxes": jnp.asarray([[1.0, 1.0, 11.0, 11.0], [20.0, 20.0, 30.0, 30.0]]),
        "labels": jnp.asarray([0, 0]),
    }]
    m = cls()
    m.update(preds, target)
    out = m.compute()
    assert np.isfinite(float(out[m._iou_type]))


# --------------------------------------------------------------------- mAP
def test_map_reference_doctest_oracle():
    preds = [dict(
        boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        scores=jnp.asarray([0.536]),
        labels=jnp.asarray([0]),
    )]
    target = [dict(
        boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        labels=jnp.asarray([0]),
    )]
    m = MeanAveragePrecision(iou_type="bbox")
    m.update(preds, target)
    res = m.compute()
    assert float(res["map"]) == pytest.approx(0.6, abs=1e-4)
    assert float(res["map_50"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_75"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_large"]) == pytest.approx(0.6, abs=1e-4)
    assert float(res["map_medium"]) == -1.0
    assert float(res["map_small"]) == -1.0
    assert float(res["mar_1"]) == pytest.approx(0.6, abs=1e-4)
    assert float(res["mar_10"]) == pytest.approx(0.6, abs=1e-4)
    assert float(res["mar_100"]) == pytest.approx(0.6, abs=1e-4)
    assert float(res["map_per_class"]) == -1.0
    assert int(res["classes"]) == 0


def test_map_hand_derived_interpolation():
    # dets (score order): TP, FP, TP over 2 gts -> pr=[1,1/2,2/3] -> monotone
    # [1,2/3,2/3]; 101-pt AP = (51*1 + 50*2/3)/101
    preds = [dict(
        boxes=jnp.asarray([
            [0.0, 0.0, 10.0, 10.0],
            [50.0, 50.0, 60.0, 60.0],
            [20.0, 20.0, 30.0, 30.0],
        ]),
        scores=jnp.asarray([0.9, 0.8, 0.7]),
        labels=jnp.asarray([0, 0, 0]),
    )]
    target = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 10.0, 10.0], [20.0, 20.0, 30.0, 30.0]]),
        labels=jnp.asarray([0, 0]),
    )]
    m = MeanAveragePrecision()
    m.update(preds, target)
    res = m.compute()
    want_ap = (51 * 1.0 + 50 * (2.0 / 3.0)) / 101
    assert float(res["map"]) == pytest.approx(want_ap, abs=1e-5)
    assert float(res["map_50"]) == pytest.approx(want_ap, abs=1e-5)
    assert float(res["mar_100"]) == pytest.approx(1.0)
    assert float(res["mar_1"]) == pytest.approx(0.5)
    # gt areas are 100 (< 32^2) -> small
    assert float(res["map_small"]) == pytest.approx(want_ap, abs=1e-5)
    assert float(res["map_large"]) == -1.0


def test_map_multiclass_and_accumulation():
    # class 0 perfect, class 1 missed -> macro map = (1 + 0)/2
    preds1 = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]),
        scores=jnp.asarray([0.9]),
        labels=jnp.asarray([0]),
    )]
    target1 = [dict(boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]), labels=jnp.asarray([0]))]
    preds2 = [dict(
        boxes=jnp.zeros((0, 4)),
        scores=jnp.zeros(0),
        labels=jnp.zeros(0, jnp.int32),
    )]
    target2 = [dict(boxes=jnp.asarray([[10.0, 10.0, 50.0, 50.0]]), labels=jnp.asarray([1]))]
    m = MeanAveragePrecision(class_metrics=True)
    m.update(preds1, target1)
    m.update(preds2, target2)
    res = m.compute()
    assert float(res["map"]) == pytest.approx(0.5, abs=1e-5)
    np.testing.assert_allclose(np.asarray(res["map_per_class"]), [1.0, 0.0], atol=1e-6)
    np.testing.assert_array_equal(np.asarray(res["classes"]), [0, 1])


def test_map_crowd_ignored():
    # crowd gt: matched det is ignored, crowd gt not counted as FN
    preds = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 10.0, 10.0]]),
        scores=jnp.asarray([0.9]),
        labels=jnp.asarray([0]),
    )]
    target = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 10.0, 10.0]]),
        labels=jnp.asarray([0]),
        iscrowd=jnp.asarray([1]),
    )]
    m = MeanAveragePrecision()
    m.update(preds, target)
    res = m.compute()
    assert float(res["map"]) == -1.0  # no valid (non-crowd) gt at all


def test_map_segm():
    mask_gt = np.zeros((1, 20, 20), bool)
    mask_gt[0, :10, :10] = True
    mask_pred = np.zeros((1, 20, 20), bool)
    mask_pred[0, :10, :8] = True  # IoU = 80/100 = 0.8
    preds = [dict(masks=jnp.asarray(mask_pred), scores=jnp.asarray([0.8]), labels=jnp.asarray([3]))]
    target = [dict(masks=jnp.asarray(mask_gt), labels=jnp.asarray([3]))]
    m = MeanAveragePrecision(iou_type="segm")
    m.update(preds, target)
    res = m.compute()
    # IoU 0.8 passes thresholds 0.5..0.8 (7 of 10) -> map = 0.7
    assert float(res["map"]) == pytest.approx(0.7, abs=1e-5)
    assert float(res["map_50"]) == pytest.approx(1.0)


def test_map_input_validation():
    m = MeanAveragePrecision()
    with pytest.raises(ValueError, match="same length"):
        m.update([], [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0))])
    with pytest.raises(ValueError, match="scores"):
        m.update(
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0))],
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0))],
        )
    with pytest.raises(ValueError, match="box_format"):
        MeanAveragePrecision(box_format="bogus")


def test_map_micro_average():
    # class 0 perfect, class 1 missed: macro map = 0.5; micro pools detections
    preds = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]),
        scores=jnp.asarray([0.9]),
        labels=jnp.asarray([0]),
    )]
    target = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0], [100.0, 100.0, 140.0, 140.0]]),
        labels=jnp.asarray([0, 1]),
    )]
    macro = MeanAveragePrecision(average="macro")
    macro.update(preds, target)
    micro = MeanAveragePrecision(average="micro")
    micro.update(preds, target)
    m_macro = float(macro.compute()["map"])
    m_micro = float(micro.compute()["map"])
    assert m_macro == pytest.approx(0.5, abs=1e-5)
    # micro: one pooled class with 2 gts, 1 TP det => recall caps at 0.5
    want_micro = 51 / 101  # precision 1 up to recall 0.5, 0 beyond
    assert m_micro == pytest.approx(want_micro, abs=1e-5)
    np.testing.assert_array_equal(np.asarray(micro.compute()["classes"]), [0, 1])


def test_map_extended_summary_ious():
    preds = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]),
        scores=jnp.asarray([0.9]),
        labels=jnp.asarray([0]),
    )]
    target = [dict(boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]), labels=jnp.asarray([0]))]
    m = MeanAveragePrecision(extended_summary=True)
    m.update(preds, target)
    res = m.compute()
    assert res["precision"].shape[0] == 10
    assert (0, 0) in res["ious"]
    assert float(res["ious"][(0, 0)][0, 0]) == pytest.approx(1.0)


def test_panoptic_large_instance_ids():
    # COCO-panoptic RGB-encoded instance ids must not overflow the pairing
    big = 16_000_000
    preds = jnp.asarray([[[[1, big], [200, big + 1]], [[1, big], [200, big + 1]]]])
    m = float(panoptic_quality(preds, preds, things={1, 200}, stuffs=set()))
    assert m == pytest.approx(1.0)


def test_map_box_format():
    # same box in xywh
    preds = [dict(
        boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]),  # xywh
        scores=jnp.asarray([0.9]),
        labels=jnp.asarray([0]),
    )]
    target = [dict(boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]), labels=jnp.asarray([0]))]
    m = MeanAveragePrecision(box_format="xywh")
    m.update(preds, target)
    assert float(m.compute()["map"]) == pytest.approx(1.0)


# ------------------------------------------------------------ panoptic quality
PQ_PREDS = jnp.asarray([[[[6, 0], [0, 0], [6, 0], [6, 0]],
                         [[0, 0], [0, 0], [6, 0], [0, 1]],
                         [[0, 0], [0, 0], [6, 0], [0, 1]],
                         [[0, 0], [7, 0], [6, 0], [1, 0]],
                         [[0, 0], [7, 0], [7, 0], [7, 0]]]])
PQ_TARGET = jnp.asarray([[[[6, 0], [0, 1], [6, 0], [0, 1]],
                          [[0, 1], [0, 1], [6, 0], [0, 1]],
                          [[0, 1], [0, 1], [6, 0], [1, 0]],
                          [[0, 1], [7, 0], [1, 0], [1, 0]],
                          [[0, 1], [7, 0], [7, 0], [7, 0]]]])


def test_panoptic_quality_oracle():
    got = float(panoptic_quality(PQ_PREDS, PQ_TARGET, things={0, 1}, stuffs={6, 7}))
    assert got == pytest.approx(0.5463, abs=1e-4)


def test_panoptic_quality_sq_rq_oracle():
    got = np.asarray(panoptic_quality(PQ_PREDS, PQ_TARGET, things={0, 1}, stuffs={6, 7}, return_sq_and_rq=True))
    np.testing.assert_allclose(got, [0.5463, 0.6111, 0.6667], atol=1e-4)


def test_panoptic_quality_per_class_oracle():
    got = np.asarray(panoptic_quality(PQ_PREDS, PQ_TARGET, things={0, 1}, stuffs={6, 7}, return_per_class=True))
    np.testing.assert_allclose(got, [[0.5185, 0.0, 0.6667, 1.0]], atol=1e-4)


MPQ_PREDS = jnp.asarray([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2], [1, 0]]])
MPQ_TARGET = jnp.asarray([[[0, 1], [0, 0], [6, 0], [7, 0], [6, 0], [255, 0]]])


def test_modified_panoptic_quality_oracle():
    got = float(modified_panoptic_quality(MPQ_PREDS, MPQ_TARGET, things={0, 1}, stuffs={6, 7}))
    assert got == pytest.approx(0.7667, abs=1e-4)


def test_panoptic_quality_class_accumulation():
    m = PanopticQuality(things={0, 1}, stuffs={6, 7})
    m.update(PQ_PREDS, PQ_TARGET)
    m.update(PQ_PREDS, PQ_TARGET)  # same twice: averages unchanged
    assert float(m.compute()) == pytest.approx(0.5463, abs=1e-4)

    m2 = ModifiedPanopticQuality(things={0, 1}, stuffs={6, 7})
    m2.update(MPQ_PREDS, MPQ_TARGET)
    assert float(m2.compute()) == pytest.approx(0.7667, abs=1e-4)


def test_panoptic_quality_validation():
    with pytest.raises(ValueError, match="distinct"):
        PanopticQuality(things={0, 1}, stuffs={1, 2})
    m = PanopticQuality(things={0}, stuffs={6})
    with pytest.raises(ValueError, match="Unknown categories"):
        m.update(jnp.asarray([[[[9, 0]]]]), jnp.asarray([[[[0, 0]]]]))
    # unknown categories in target always map to void, no error
    m2 = PanopticQuality(things={0}, stuffs={6}, allow_unknown_preds_category=True)
    m2.update(jnp.asarray([[[[0, 0]]]]), jnp.asarray([[[[9, 0]]]]))
