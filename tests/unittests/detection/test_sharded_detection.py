"""Detection mAP through the 8-device ragged sharded-sync path.

The reference treats mAP's sync as special enough to need a custom
``_sync_dist`` (pad every per-image tensor to the world max, all_gather,
trim — /root/reference/src/torchmetrics/detection/mean_ap.py:1022-1046 +
utilities/distributed.py:136-147).  These tests push the repo's equivalent
(:func:`torchmetrics_tpu.parallel.sync_ragged_states`) across a real
8-device mesh with *uneven* per-device image counts and det/gt counts —
including a device that saw no images at all — and assert the merged state
computes identically to single-device accumulation and the torch oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.helpers.refpath import add_reference_paths
from tests.helpers.sharded import assert_results_close

add_reference_paths()

import jax.numpy as jnp  # noqa: E402

from torchmetrics_tpu.detection import MeanAveragePrecision  # noqa: E402
from torchmetrics_tpu.parallel import sharded_list_update, sync_ragged_states  # noqa: E402

UNBANDED_KEYS = ("map", "map_50", "map_75", "mar_1", "mar_10", "mar_100")


def _ragged_images(seed: int, n_images: int, n_classes: int = 3, allow_empty: bool = True):
    """Per-image (pred_dict, target_dict) with varying det/gt counts, incl.
    zero-det and zero-gt images."""
    rng = np.random.default_rng(seed)
    images = []
    for i in range(n_images):
        ng = int(rng.integers(0 if allow_empty else 1, 7))
        xy = rng.uniform(0, 150, (ng, 2))
        wh = rng.uniform(8, 100, (ng, 2))
        gb = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        gl = rng.integers(0, n_classes, ng)
        keep = rng.uniform(0, 1, ng) < 0.8
        pb = gb[keep] + rng.normal(0, 4, (int(keep.sum()), 4)).astype(np.float32)
        pl = gl[keep].copy()
        nfp = int(rng.integers(0, 4))
        fp_xy = rng.uniform(0, 150, (nfp, 2))
        fp_wh = rng.uniform(8, 60, (nfp, 2))
        pb = np.concatenate([pb, np.concatenate([fp_xy, fp_xy + fp_wh], 1).astype(np.float32)])
        pl = np.concatenate([pl, rng.integers(0, n_classes, nfp)])
        ps = rng.uniform(0.1, 1, len(pl)).astype(np.float32)
        pred = {"boxes": jnp.asarray(pb.reshape(-1, 4)), "scores": jnp.asarray(ps),
                "labels": jnp.asarray(pl.astype(np.int32))}
        target = {"boxes": jnp.asarray(gb.reshape(-1, 4)), "labels": jnp.asarray(gl.astype(np.int32))}
        images.append((pred, target))
    return images


def _uneven_split(images, n_dev: int, seed: int):
    """Assign images to devices with deliberately unequal counts; device 1
    (when present) gets nothing — the all-empty-shard edge the reference's
    pad-gather path must survive."""
    rng = np.random.default_rng(seed + 1000)
    assignment = rng.integers(0, n_dev, len(images))
    if n_dev > 1:
        assignment[assignment == 1] = 0  # starve device 1
    per_dev = []
    for d in range(n_dev):
        mine = [images[i] for i in np.nonzero(assignment == d)[0]]
        per_dev.append(([p for p, _ in mine], [t for _, t in mine]))
    counts = [len(b[0]) for b in per_dev]
    assert min(counts) == 0 and max(counts) >= 3, f"split not uneven enough: {counts}"
    return per_dev


@pytest.mark.parametrize("seed", [5, 19])
def test_sharded_map_ragged_uneven_devices(mesh, seed):
    images = _ragged_images(seed, n_images=16)
    n_dev = mesh.devices.size
    per_dev = _uneven_split(images, n_dev, seed)

    single = MeanAveragePrecision(class_metrics=True)
    for preds, targets in per_dev:  # same order the mesh path merges in
        if preds:
            single.update(preds, targets)
    expected = single.compute()

    sharded = MeanAveragePrecision(class_metrics=True)
    state = sharded_list_update(sharded, per_dev, mesh=mesh)
    # every image crossed the mesh exactly once
    assert len(state["detection_scores"]) == sum(len(b[0]) for b in per_dev)
    got = sharded.compute_state(state)
    assert_results_close(got, expected, atol=1e-6, rtol=1e-6, label="sharded-map-vs-single")


def test_sharded_map_matches_torch_oracle(mesh):
    """Mesh-synced mAP ≡ the reference's pure-torch evaluator on the same
    ragged dataset (crowd-free: the legacy oracle has no crowd handling —
    see test_map_oracle.py scope notes)."""
    from tests.helpers.refpath import require_reference

    require_reference()  # skips when the reference mount / torchmetrics is absent
    torch = pytest.importorskip("torch")
    from torchmetrics.detection._mean_ap import MeanAveragePrecision as LegacyMAP

    images = _ragged_images(23, n_images=12, allow_empty=False)
    per_dev = _uneven_split(images, mesh.devices.size, 23)

    legacy = LegacyMAP()
    for preds, targets in per_dev:
        if not preds:
            continue
        legacy.update(
            [{k: torch.tensor(np.asarray(v)) for k, v in p.items()} for p in preds],
            [{k: torch.tensor(np.asarray(v)) for k, v in t.items()} for t in targets],
        )
    oracle = legacy.compute()

    ours = MeanAveragePrecision()
    state = sharded_list_update(ours, per_dev, mesh=mesh)
    got = ours.compute_state(state)
    for k in UNBANDED_KEYS:
        np.testing.assert_allclose(float(got[k]), float(oracle[k]), atol=1e-5, err_msg=k)


def test_sharded_map_crowd_state_survives_mesh(mesh):
    """Crowd flags and user-provided areas are list states too — they must
    cross the mesh bit-exactly (sharded ≡ single includes the crowd keys)."""
    rng = np.random.default_rng(3)
    images = []
    for _ in range(8):
        ng = int(rng.integers(1, 5))
        xy = rng.uniform(0, 100, (ng, 2))
        wh = rng.uniform(10, 80, (ng, 2))
        gb = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        gl = rng.integers(0, 2, ng).astype(np.int32)
        crowd = (rng.uniform(0, 1, ng) < 0.3).astype(np.int32)
        pb = gb + rng.normal(0, 3, gb.shape).astype(np.float32)
        ps = rng.uniform(0.1, 1, ng).astype(np.float32)
        images.append((
            {"boxes": jnp.asarray(pb), "scores": jnp.asarray(ps), "labels": jnp.asarray(gl)},
            {"boxes": jnp.asarray(gb), "labels": jnp.asarray(gl), "iscrowd": jnp.asarray(crowd)},
        ))
    per_dev = _uneven_split(images, mesh.devices.size, 3)

    single = MeanAveragePrecision()
    for preds, targets in per_dev:
        if preds:
            single.update(preds, targets)
    expected = single.compute()

    sharded = MeanAveragePrecision()
    state = sharded_list_update(sharded, per_dev, mesh=mesh)
    got = sharded.compute_state(state)
    assert_results_close(got, expected, atol=1e-6, rtol=1e-6, label="sharded-map-crowd")


def test_sharded_map_segm_masks_cross_mesh(mesh):
    """Mask (segm) states are (k, H, W) tensors ragged in EVERY dim — images
    of different sizes give different H, W per item, so the pad must cover
    trailing dims too (the reference pads all dims to the world max,
    utilities/distributed.py:136-147)."""
    rng = np.random.default_rng(9)
    images = []
    for _ in range(8):
        n = int(rng.integers(1, 4))
        hw = int(rng.integers(24, 48))  # per-image mask size varies
        masks = np.zeros((n, hw, hw), bool)
        for j in range(n):
            x0, y0 = rng.integers(0, hw // 2, 2)
            w, h = rng.integers(6, 14, 2)
            masks[j, y0 : y0 + h, x0 : x0 + w] = True
        noisy = masks.copy()
        noisy[:, ::7, :] = False
        lab = rng.integers(0, 2, n).astype(np.int32)
        images.append((
            {"masks": jnp.asarray(noisy), "scores": jnp.asarray(rng.uniform(0.2, 1, n).astype(np.float32)),
             "labels": jnp.asarray(lab)},
            {"masks": jnp.asarray(masks), "labels": jnp.asarray(lab)},
        ))
    per_dev = _uneven_split(images, mesh.devices.size, 9)

    single = MeanAveragePrecision(iou_type="segm")
    for preds, targets in per_dev:
        if preds:
            single.update(preds, targets)
    expected = single.compute()

    sharded = MeanAveragePrecision(iou_type="segm")
    state = sharded_list_update(sharded, per_dev, mesh=mesh)
    got = sharded.compute_state(state)
    assert_results_close(got, expected, atol=1e-6, rtol=1e-6, label="sharded-map-segm")


def test_sharded_list_update_rejects_overridden_sync(mesh):
    """A metric whose sync_states is overridden does not combine leaf-wise —
    the ragged path must refuse loudly instead of applying the table."""
    from torchmetrics_tpu.regression import PearsonCorrCoef

    metric = PearsonCorrCoef()
    with pytest.raises(ValueError, match="overrides sync_states"):
        sharded_list_update(metric, [((), ())] * mesh.devices.size, mesh=mesh)


def test_sync_ragged_states_device_order_and_lengths(mesh):
    """Unit-level check of the pad-gather-trim primitive itself: items come
    back in device order with exact lengths and values."""
    n_dev = mesh.devices.size
    reductions = {"items": None}
    per_dev = []
    for d in range(n_dev):
        k = d % 3  # 0, 1 or 2 items per device
        items = tuple(
            jnp.asarray(np.full((d + j + 1, 2), 10 * d + j, np.float32)) for j in range(k)
        )
        per_dev.append({"items": items, "_n": jnp.asarray(1 if k else 0, jnp.int32)})

    from torchmetrics_tpu.core.reductions import canonical_reduce

    merged = sync_ragged_states(
        {k: canonical_reduce(v) for k, v in reductions.items()}, per_dev, mesh
    )
    expected_items = [it for st in per_dev for it in st["items"]]
    assert len(merged["items"]) == len(expected_items)
    for got, exp in zip(merged["items"], expected_items):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))
    assert int(merged["_n"]) == sum(int(st["_n"]) for st in per_dev)
