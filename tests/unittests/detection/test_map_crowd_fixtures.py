"""Hand-derived COCO crowd-semantics fixtures for MeanAveragePrecision.

pycocotools cannot run in this image (not installed, and the COCO sample
jsons the reference's test_map.py uses are not mounted), so these fixtures
are derived BY HAND from the COCOeval algorithm (cocoeval.py evaluateImg/
accumulate), with every step written out.  Each case is constructed so that
an implementation missing the specific crowd rule produces a DIFFERENT
number — they discriminate, not just exercise:

  1. crowd multi-match: a crowd gt absorbs several high-scoring dets that
     a crowd-blind evaluator would count as score-leading FPs;
  2. non-ignored priority: a lower-IoU non-crowd gt must win over a
     higher-IoU overlapping crowd gt;
  3. area-range interplay: crowd ignore + out-of-range unmatched-det
     ignore inside the small/medium/large splits;
  4. threshold-dependent crowd eligibility: a det is crowd-ignored at
     IoU .5 but becomes a real FP at .55+.

Reference gold standard these rules mirror: pycocotools semantics as
embedded in the reference (detection/mean_ap.py:528 delegates to COCOeval).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.detection import MeanAveragePrecision


def _image(preds_rows, gt_rows):
    """rows: (box, score|None, label, iscrowd-for-gt)."""
    preds = dict(
        boxes=jnp.asarray([r[0] for r in preds_rows], jnp.float32).reshape(-1, 4),
        scores=jnp.asarray([r[1] for r in preds_rows], jnp.float32),
        labels=jnp.asarray([r[2] for r in preds_rows], jnp.int32),
    )
    target = dict(
        boxes=jnp.asarray([r[0] for r in gt_rows], jnp.float32).reshape(-1, 4),
        labels=jnp.asarray([r[1] for r in gt_rows], jnp.int32),
        iscrowd=jnp.asarray([r[2] for r in gt_rows], jnp.int32),
    )
    return [preds], [target]


@pytest.mark.parametrize("backend", ["native", "native_numpy"])
def test_crowd_absorbs_score_leading_dets(backend):
    """Case 1: crowd gt absorbs TWO dets that outscore / follow the TP.

    gts:  A=[0,0,10,10] (real), B=[20,20,40,40] (crowd)
    dets: d2=[20,20,30,30] s=.95 — crowd IoU vs B = 100/100 = 1.0 (union is
            the DET area for crowd) -> matched to B -> ignored
          d1=[0,0,10,10]  s=.90 — IoU vs A = 1.0 -> TP
          d3=[25,25,35,35] s=.70 — crowd IoU vs B = 1.0; B is already
            matched but crowd gts accept multiple matches -> ignored
          d4=[60,60,70,70] s=.60 — no overlap -> FP
    All these IoUs are exact 1.0/0.0, so every IoU threshold behaves alike.
    nGT (non-ignored) = 1.

    Score-ordered NON-IGNORED dets: d1 TP (p=1, r=1), d4 FP.  The 101-point
    envelope has precision 1.0 at recall 1.0 -> AP = 1.0 at all thresholds.

    A crowd-blind evaluator counts d2 as the top-scoring FP: the envelope at
    recall 1 drops to 1/2 -> AP = 0.5.  This case separates the two.

    mar_1: with maxDets=1 only d2 survives the cap; it is crowd-ignored, so
    no non-ignored det exists -> recall 0.
    """
    preds, target = _image(
        [([20, 20, 30, 30], 0.95, 0), ([0, 0, 10, 10], 0.90, 0),
         ([25, 25, 35, 35], 0.70, 0), ([60, 60, 70, 70], 0.60, 0)],
        [([0, 0, 10, 10], 0, 0), ([20, 20, 40, 40], 0, 1)],
    )
    m = MeanAveragePrecision(backend=backend)
    m.update(preds, target)
    res = m.compute()
    assert float(res["map"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_50"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_75"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["mar_100"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["mar_1"]) == pytest.approx(0.0, abs=1e-6)


@pytest.mark.parametrize("backend", ["native", "native_numpy"])
def test_non_crowd_priority_beats_higher_iou_crowd(backend):
    """Case 2: the matcher must prefer a lower-IoU REAL gt over a
    higher-IoU crowd gt.

    gts:  A=[0,0,10,10] (real), C=[0,0,12,12] (crowd), overlapping.
    det:  d1=[0,0,11,11] s=.9
          IoU vs A      = 100 / (121 + 100 - 100) = 100/121 ~= 0.8264
          crowd IoU vs C = 121 / 121 = 1.0  (union = det area)

    COCOeval scans non-ignored gts first and KEEPS a non-ignored match even
    when an ignored gt has higher IoU.  So for t in {.50...80} (7 of the 10
    thresholds, 0.8264 >= t): d1 -> A, TP, AP(t) = 1.  For t in {.85,.90,.95}
    A is ineligible and d1 matches the crowd -> ignored; no non-ignored det
    and recall 0 -> AP(t) = 0.

    map = 7/10 = 0.7; a highest-IoU-first matcher would send d1 to the
    crowd at EVERY threshold -> map = 0.
    """
    preds, target = _image(
        [([0, 0, 11, 11], 0.9, 0)],
        [([0, 0, 10, 10], 0, 0), ([0, 0, 12, 12], 0, 1)],
    )
    m = MeanAveragePrecision(backend=backend)
    m.update(preds, target)
    res = m.compute()
    assert float(res["map"]) == pytest.approx(0.7, abs=1e-6)
    assert float(res["map_50"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_75"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["mar_100"]) == pytest.approx(0.7, abs=1e-6)


@pytest.mark.parametrize("backend", ["native", "native_numpy"])
def test_crowd_and_area_ranges(backend):
    """Case 3: crowd ignore composes with the small/medium/large splits and
    with the unmatched-out-of-range det rule.

    gts:  A=[0,0,10,10] real, area 100 (small: < 32^2)
          B=[20,20,40,40] crowd, area 400 (small)
    dets: d3=[50,50,90,90] s=.95, area 1600 (medium), no overlap
          d1=[0,0,10,10]  s=.90 -> TP on A (IoU 1.0)
          d2=[20,20,30,30] s=.80 -> crowd-ignored on B

    "all" range: d3 is in range -> real top-scoring FP; sequence d3 FP,
    d1 TP => precision at recall 1 is 1/2 -> AP = 0.5 at all thresholds.

    "small" range: d3 is OUT of range and unmatched -> ignored (not FP);
    d1 TP, d2 crowd-ignored -> AP_small = 1.0.  A rule-blind evaluator
    counts d3 -> 0.5.

    "medium"/"large": no non-ignored gt at all -> -1 sentinel.
    """
    preds, target = _image(
        [([50, 50, 90, 90], 0.95, 0), ([0, 0, 10, 10], 0.90, 0), ([20, 20, 30, 30], 0.80, 0)],
        [([0, 0, 10, 10], 0, 0), ([20, 20, 40, 40], 0, 1)],
    )
    m = MeanAveragePrecision(backend=backend)
    m.update(preds, target)
    res = m.compute()
    assert float(res["map"]) == pytest.approx(0.5, abs=1e-6)
    assert float(res["map_small"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_medium"]) == pytest.approx(-1.0, abs=1e-6)
    assert float(res["map_large"]) == pytest.approx(-1.0, abs=1e-6)
    assert float(res["mar_100"]) == pytest.approx(1.0, abs=1e-6)


@pytest.mark.parametrize("backend", ["native", "native_numpy"])
def test_crowd_eligibility_is_threshold_dependent(backend):
    """Case 4: a det crowd-matches at IoU .5 exactly, and becomes a true FP
    at every higher threshold.

    gts:  A=[0,0,10,10] real; B=[20,20,40,40] crowd
    dets: d5=[15,25,25,35] s=.95
            inter with B: x [20,25]=5, y [25,35]=10 -> 50; det area 100
            crowd IoU = 50/100 = 0.5 exactly
          d1=[0,0,10,10] s=.90 -> IoU 1.0 vs A

    t=.50: d5 -> crowd-ignored; d1 TP -> AP = 1.0
    t>=.55: d5 unmatched, in range -> FP ahead of the TP; envelope at
            recall 1 = 1/2 -> AP = 0.5
    map = (1.0 + 9*0.5)/10 = 0.55; map_50 = 1.0; map_75 = 0.5.

    (The exact-0.5 IoU also pins the >= comparison and the float32
    tie-break shared by both backends.)
    """
    preds, target = _image(
        [([15, 25, 25, 35], 0.95, 0), ([0, 0, 10, 10], 0.90, 0)],
        [([0, 0, 10, 10], 0, 0), ([20, 20, 40, 40], 0, 1)],
    )
    m = MeanAveragePrecision(backend=backend)
    m.update(preds, target)
    res = m.compute()
    assert float(res["map"]) == pytest.approx(0.55, abs=1e-6)
    assert float(res["map_50"]) == pytest.approx(1.0, abs=1e-6)
    assert float(res["map_75"]) == pytest.approx(0.5, abs=1e-6)
