"""mAP against recorded external-oracle fixtures (VERDICT r4 next #9).

`tests/fixtures/map_crowd_recorded.json` holds pycocotools COCOeval numbers
for a seeded crowd-heavy dataset; the generation script
(tests/fixtures/generate_fixtures.py) fills them wherever pycocotools exists.
When the fixture is still ``pending`` (this zero-egress image) the strict
assertion skips cleanly — the hand-derived crowd vectors always assert.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.detection import MeanAveragePrecision

FIXTURES = os.path.join(os.path.dirname(__file__), "..", "..", "fixtures")


def _load(name):
    with open(os.path.join(FIXTURES, name)) as handle:
        return json.load(handle)


def test_map_crowd_recorded_pycocotools():
    fix = _load("map_crowd_recorded.json")
    if fix["provenance"] == "pending" or fix["expected"] is None:
        pytest.skip("fixture awaiting pycocotools regeneration (generate_fixtures.py --write)")

    import sys

    sys.path.insert(0, FIXTURES)
    from generate_fixtures import map_crowd_dataset

    m = MeanAveragePrecision()
    for im in map_crowd_dataset():
        m.update(
            [dict(boxes=jnp.asarray(im["det_boxes"], jnp.float32).reshape(-1, 4),
                  scores=jnp.asarray(im["det_scores"], jnp.float32),
                  labels=jnp.asarray(im["det_labels"], jnp.int32))],
            [dict(boxes=jnp.asarray(im["gt_boxes"], jnp.float32).reshape(-1, 4),
                  labels=jnp.asarray(im["gt_labels"], jnp.int32),
                  iscrowd=jnp.asarray(im["gt_crowd"], jnp.int32))],
        )
    res = m.compute()
    for key, expected in fix["expected"].items():
        np.testing.assert_allclose(float(res[key]), expected, atol=1e-6, err_msg=key)


def test_map_crowd_handderived_vectors():
    """The committed hand-derived COCOeval vectors always assert — they are
    the recorded values the pending pycocotools replay will cross-check."""
    fix = _load("map_crowd_handderived.json")
    assert fix["provenance"] == "hand-derived-cocoeval"
    expected = {name: case["map"] for name, case in fix["cases"].items()}
    assert expected == {
        "crowd_absorbs_score_leading_dets": 1.0,
        "crowd_and_area_ranges": 0.5,
        "crowd_eligibility_threshold_dependent": 0.55,
    }
    # the vectors are enforced against the evaluator in
    # test_map_crowd_fixtures.py (both backends); here we pin the fixture
    # file itself so a drive-by edit of the recorded numbers fails loudly


def test_generation_script_reports_cleanly():
    """The generator must degrade to a report (not a crash) without the tools."""
    import subprocess
    import sys

    res = subprocess.run(
        [sys.executable, os.path.join(FIXTURES, "generate_fixtures.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr[-500:]
    assert "stoi_recorded.json" in res.stdout and "map_crowd_recorded.json" in res.stdout
