"""mAP parity vs the reference's in-tree pure-torch COCO evaluator.

The reference ships a complete pure-torch mAP (`detection/_mean_ap.py:148`,
upstream-validated against pycocotools) alongside the pycocotools-backed
one.  pycocotools is not installed in this image, but the torch evaluator
runs here with tiny torchvision/pycocotools import stubs
(tests/helpers/stubs/) — so it serves as a live, independent oracle for the
native evaluator on randomized datasets, far beyond the frozen doctest
values in test_detection.py.

Scope notes (two *verified* legacy-oracle defects, excluded from scope):
1. The legacy torch evaluator has NO crowd handling (grep "iscrowd" in
   `_mean_ap.py` → nothing), so the oracle comparisons run crowd-free;
   pycocotools crowd semantics (ignore + union=det-area + re-matchable) are
   covered by the hand-derived cases in test_detection.py.
2. The legacy evaluator mis-scores detections whose best gt is
   area-range-ignored once the IoU drops below threshold at the higher
   thresholds (hand-derivation in test_map_area_ignored_fp_transition
   below: COCOeval semantics give 0.5919, the legacy gives 0.4252) — so
   the area-banded keys are compared on single-band datasets where gt
   ignore never triggers.
"""

from __future__ import annotations


import numpy as np
import pytest

from tests.helpers.refpath import require_reference

require_reference()

import torch  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from torchmetrics_tpu.detection import MeanAveragePrecision  # noqa: E402

GLOBAL_KEYS = (
    "map", "map_50", "map_75", "map_small", "map_medium", "map_large",
    "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large",
)


def _dataset(seed: int, n_images: int = 8, n_classes: int = 4):
    """Jittered-gt detections + false positives across all COCO area ranges."""
    rng = np.random.default_rng(seed)
    batches = []
    for _ in range(n_images):
        ng = int(rng.integers(1, 9))
        xy = rng.uniform(0, 150, (ng, 2))
        wh = rng.uniform(4, 120, (ng, 2))
        gb = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        gl = rng.integers(0, n_classes, ng)
        keep = rng.uniform(0, 1, ng) < 0.85
        pb = gb[keep] + rng.normal(0, 3, (int(keep.sum()), 4)).astype(np.float32)
        pl = gl[keep].copy()
        flip = rng.uniform(0, 1, len(pl)) < 0.15
        pl[flip] = rng.integers(0, n_classes, int(flip.sum()))
        nfp = int(rng.integers(0, 4))
        fp_xy = rng.uniform(0, 150, (nfp, 2))
        fp_wh = rng.uniform(4, 60, (nfp, 2))
        pb = np.concatenate([pb, np.concatenate([fp_xy, fp_xy + fp_wh], 1).astype(np.float32)])
        pl = np.concatenate([pl, rng.integers(0, n_classes, nfp)])
        ps = rng.uniform(0.1, 1, len(pl)).astype(np.float32)
        batches.append((pb, ps, pl, gb, gl))
    return batches


def _run_both(batches, **kwargs):
    from torchmetrics.detection._mean_ap import MeanAveragePrecision as LegacyMAP

    legacy = LegacyMAP(**kwargs)
    ours = MeanAveragePrecision(**kwargs)
    for pb, ps, pl, gb, gl in batches:
        legacy.update(
            [{"boxes": torch.tensor(pb), "scores": torch.tensor(ps), "labels": torch.tensor(pl)}],
            [{"boxes": torch.tensor(gb), "labels": torch.tensor(gl)}],
        )
        ours.update(
            [{"boxes": jnp.asarray(pb), "scores": jnp.asarray(ps), "labels": jnp.asarray(pl)}],
            [{"boxes": jnp.asarray(gb), "labels": jnp.asarray(gl)}],
        )
    return legacy.compute(), ours.compute()


UNBANDED_KEYS = ("map", "map_50", "map_75", "mar_1", "mar_10", "mar_100")


@pytest.mark.parametrize("seed", [7, 11, 23, 42])
def test_map_matches_torch_oracle(seed):
    lres, ores = _run_both(_dataset(seed), class_metrics=True)
    for k in UNBANDED_KEYS:
        np.testing.assert_allclose(float(ores[k]), float(lres[k]), atol=1e-5, err_msg=k)
    np.testing.assert_allclose(
        np.asarray(ores["map_per_class"]), np.asarray(lres["map_per_class"]), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(ores["mar_100_per_class"]), np.asarray(lres["mar_100_per_class"]), atol=1e-5
    )


def _single_band_dataset(seed: int, lo: float, hi: float, n_images: int = 6):
    """All boxes in one COCO area band so gt area-ignore never triggers and
    the banded keys are safe to compare against the legacy oracle."""
    rng = np.random.default_rng(seed)
    side_lo, side_hi = np.sqrt(lo) * 1.15, np.sqrt(hi) * 0.85
    batches = []
    for _ in range(n_images):
        ng = int(rng.integers(2, 7))
        xy = rng.uniform(0, 100, (ng, 2))
        wh = rng.uniform(side_lo, side_hi, (ng, 2))
        gb = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        gl = rng.integers(0, 3, ng)
        pb = gb + rng.normal(0, np.sqrt(lo) * 0.08, gb.shape).astype(np.float32)
        ps = rng.uniform(0.1, 1, ng).astype(np.float32)
        batches.append((pb, ps, gl, gb, gl))
    return batches


@pytest.mark.parametrize("band,lo,hi", [("small", 16.0, 32.0**2), ("medium", 32.0**2, 96.0**2), ("large", 96.0**2, 144.0**2)])
def test_map_area_bands_match_torch_oracle(band, lo, hi):
    lres, ores = _run_both(_single_band_dataset(13, lo, hi))
    for k in UNBANDED_KEYS + (f"map_{band}", f"mar_{band}"):
        np.testing.assert_allclose(float(ores[k]), float(lres[k]), atol=1e-5, err_msg=k)
    # the in-band key equals the all-areas key; off-band keys are empty (-1)
    np.testing.assert_allclose(float(ores[f"map_{band}"]), float(ores["map"]), atol=1e-6)
    for other in {"small", "medium", "large"} - {band}:
        assert float(ores[f"map_{other}"]) == -1.0


def test_map_matches_torch_oracle_custom_thresholds():
    lres, ores = _run_both(
        _dataset(3),
        iou_thresholds=[0.3, 0.55, 0.8],
        rec_thresholds=list(np.round(np.linspace(0, 1, 41), 3)),
        max_detection_thresholds=[2, 5, 50],
    )
    for k in ("map", "mar_2", "mar_5", "mar_50"):
        np.testing.assert_allclose(float(ores[k]), float(lres[k]), atol=1e-5, err_msg=k)


def test_map_area_ignored_fp_transition():
    """COCOeval semantics for a det matching an area-ignored gt, frozen from
    a hand derivation (the legacy torch evaluator gets this wrong: 0.4252).

    For area range "medium" ([32², 96²]): g2 (area≈889) is ignored.  IoUs:
    d2↔g2=0.716, d1↔g1=0.818, d0↔g0=0.766; d3 is tiny (out of range).  At
    t=0.50..0.70 d2 matches ignored g2 → d2 ignored, AP=1.0 (d1,d0 TPs on
    npig=2).  At t=0.75 d2 fails the match and becomes the TOP-SCORED FP →
    precision [0, 1/2, 2/3] → AP=2/3.  At t=0.80 d0 also fails → AP=51·0.5/101.
    ≥0.85 → 0.  mAP_medium = (5·1.0 + 2/3 + 0.2525)/10 = 0.59191.
    """
    pb = np.asarray([
        [23.47217, 91.38351, 116.382, 115.39956],
        [52.8158, 148.08603, 146.81584, 187.8417],
        [89.45802, 125.134125, 132.52275, 153.96022],
        [97.39332, 144.60524, 117.031395, 152.14868],
    ], np.float32)
    ps = np.asarray([0.4835751, 0.72682524, 0.9326681, 0.21393187], np.float32)
    gb = np.asarray([
        [26.303137, 92.33771, 117.52927, 120.37504],
        [57.40053, 143.82658, 147.6035, 190.01279],
        [93.41908, 130.0844, 131.91368, 153.17079],
    ], np.float32)
    m = MeanAveragePrecision()
    m.update(
        [{"boxes": jnp.asarray(pb), "scores": jnp.asarray(ps), "labels": jnp.zeros(4, jnp.int32)}],
        [{"boxes": jnp.asarray(gb), "labels": jnp.zeros(3, jnp.int32)}],
    )
    res = m.compute()
    expected = (5 * 1.0 + 2.0 / 3.0 + 51 * 0.5 / 101) / 10
    np.testing.assert_allclose(float(res["map_medium"]), expected, atol=1e-4)


def test_map_matches_torch_oracle_xywh():
    batches = _dataset(5)
    batches = [
        (np.stack([pb[:, 0], pb[:, 1], pb[:, 2] - pb[:, 0], pb[:, 3] - pb[:, 1]], 1), ps, pl,
         np.stack([gb[:, 0], gb[:, 1], gb[:, 2] - gb[:, 0], gb[:, 3] - gb[:, 1]], 1), gl)
        for pb, ps, pl, gb, gl in batches
    ]
    lres, ores = _run_both(batches, box_format="xywh")
    for k in GLOBAL_KEYS:
        np.testing.assert_allclose(float(ores[k]), float(lres[k]), atol=1e-5, err_msg=k)


def test_map_tuple_iou_types_match_single_runs():
    """iou_type=("bbox","segm") must equal the two single-type runs with
    prefixed keys (reference mean_ap.py:375,520)."""
    rng = np.random.default_rng(0)

    def boxes_and_masks(n):
        xy = rng.uniform(0, 40, (n, 2))
        wh = rng.uniform(5, 20, (n, 2))
        b = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        masks = np.zeros((n, 70, 70), bool)
        for j, bb in enumerate(b):
            masks[j, int(bb[1]):int(bb[3]) + 1, int(bb[0]):int(bb[2]) + 1] = True
        return b, masks

    both = MeanAveragePrecision(iou_type=("bbox", "segm"))
    only_box = MeanAveragePrecision(iou_type="bbox")
    only_segm = MeanAveragePrecision(iou_type="segm")
    for _ in range(3):
        ng = int(rng.integers(2, 6))
        gb, gm = boxes_and_masks(ng)
        gl = rng.integers(0, 3, ng)
        pb = gb + rng.normal(0, 2, gb.shape).astype(np.float32)
        pm = gm.copy()
        ps = rng.uniform(0.2, 1, ng).astype(np.float32)
        p = {"boxes": jnp.asarray(pb), "masks": jnp.asarray(pm), "scores": jnp.asarray(ps), "labels": jnp.asarray(gl)}
        t = {"boxes": jnp.asarray(gb), "masks": jnp.asarray(gm), "labels": jnp.asarray(gl)}
        both.update([p], [t])
        only_box.update([p], [t])
        only_segm.update([p], [t])

    res = both.compute()
    res_b = only_box.compute()
    res_s = only_segm.compute()
    # segm keys match the single segm run exactly (same gt mask areas); bbox
    # banded keys may legitimately differ from a single bbox run because the
    # multi-type gt area is mask-derived (reference mean_ap.py:914), so only
    # the unbanded bbox keys are asserted
    for k in GLOBAL_KEYS:
        np.testing.assert_allclose(float(res[f"segm_{k}"]), float(res_s[k]), atol=1e-6, err_msg=f"segm_{k}")
    for k in ("map", "map_50", "map_75", "mar_1", "mar_10", "mar_100"):
        np.testing.assert_allclose(float(res[f"bbox_{k}"]), float(res_b[k]), atol=1e-6, err_msg=f"bbox_{k}")
    assert "classes" in res


def test_map_tuple_iou_types_require_both_keys():
    m = MeanAveragePrecision(iou_type=("bbox", "segm"))
    with pytest.raises(ValueError, match="masks"):
        m.update(
            [{"boxes": jnp.zeros((1, 4)), "scores": jnp.ones(1), "labels": jnp.zeros(1, jnp.int32)}],
            [{"boxes": jnp.zeros((1, 4)), "masks": jnp.zeros((1, 4, 4), bool), "labels": jnp.zeros(1, jnp.int32)}],
        )
