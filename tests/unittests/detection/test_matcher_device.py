"""Device matcher ≡ numpy oracle, including crowd and area-ignore cases.

The batched jitted matcher (functional/detection/matcher.py) must reproduce
`_evaluate_image`'s greedy semantics bit-for-bit; the full-metric test runs
both backends end-to-end on data with crowds (which the torch-oracle suite
cannot cover, see test_map_oracle.py).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu.detection import MeanAveragePrecision
from torchmetrics_tpu.detection.mean_ap import _AREA_RANGES, _evaluate_image
from torchmetrics_tpu.functional.detection.matcher import match_batch_padded

IOU_THRS = np.round(np.arange(0.5, 1.0, 0.05), 2)


AREA_NAMES = tuple(_AREA_RANGES)


@pytest.mark.parametrize("seed", range(6))
def test_matcher_matches_numpy_oracle(seed):
    """Per (item, area): the (A, T, D) device output equals the numpy greedy
    matcher.  Quantized ious manufacture exact ties; gts are passed UNSORTED
    to the device path (priority + original-index tie-break must reproduce
    the oracle's ignored-last stable sort)."""
    rng = np.random.default_rng(seed)
    items, oracle = [], []
    for _ in range(12):
        nd, ng = int(rng.integers(0, 14)), int(rng.integers(0, 9))
        ious = np.round(rng.uniform(0, 1, (nd, ng)), 1)
        scores = rng.uniform(0, 1, nd)
        crowd = rng.uniform(0, 1, ng) < 0.3
        g_area = rng.uniform(10, 10_000, ng)
        d_area = rng.uniform(10, 10_000, nd)
        mdet = 10
        if nd == 0 and ng == 0:
            continue
        per_area = [
            _evaluate_image(ious, scores, crowd, g_area, d_area, IOU_THRS, _AREA_RANGES[a], mdet)
            for a in AREA_NAMES
        ]
        oracle.append((per_area, d_area, scores, mdet))
        d_order = np.argsort(-scores, kind="stable")[:mdet]
        gt_ignore = np.stack([
            crowd | (g_area < _AREA_RANGES[a][0]) | (g_area > _AREA_RANGES[a][1]) for a in AREA_NAMES
        ])
        items.append((ious[d_order], crowd, gt_ignore))

    results = match_batch_padded(items, IOU_THRS)
    for (per_area, d_area, scores, mdet), (matched, ig_m) in zip(oracle, results):
        d_order = np.argsort(-scores, kind="stable")[:mdet]
        d_area_s = d_area[d_order]
        for ai, aname in enumerate(AREA_NAMES):
            tp_o, ig_o, sc_o, nv_o = per_area[ai]
            arng = _AREA_RANGES[aname]
            out_rng = (d_area_s < arng[0]) | (d_area_s > arng[1])
            ig_full = ig_m[ai] | (~matched[ai] & out_rng[None, :])
            np.testing.assert_array_equal(matched[ai], tp_o, err_msg=aname)
            np.testing.assert_array_equal(ig_full, ig_o, err_msg=aname)


def _crowd_dataset(seed, n_images=6):
    rng = np.random.default_rng(seed)
    batches = []
    for _ in range(n_images):
        ng = int(rng.integers(1, 8))
        xy = rng.uniform(0, 120, (ng, 2))
        wh = rng.uniform(4, 100, (ng, 2))
        gb = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        gl = rng.integers(0, 3, ng)
        crowd = (rng.uniform(0, 1, ng) < 0.3).astype(np.int64)
        keep = rng.uniform(0, 1, ng) < 0.85
        pb = gb[keep] + rng.normal(0, 3, (int(keep.sum()), 4)).astype(np.float32)
        ps = rng.uniform(0.1, 1, len(pb)).astype(np.float32)
        batches.append((pb, ps, gl[keep], gb, gl, crowd))
    return batches


@pytest.mark.parametrize("seed", [0, 9])
def test_full_metric_backends_agree_with_crowds(seed):
    m_dev = MeanAveragePrecision(class_metrics=True, backend="native")
    m_np = MeanAveragePrecision(class_metrics=True, backend="native_numpy")
    for pb, ps, pl, gb, gl, crowd in _crowd_dataset(seed):
        p = [{"boxes": jnp.asarray(pb), "scores": jnp.asarray(ps), "labels": jnp.asarray(pl)}]
        t = [{"boxes": jnp.asarray(gb), "labels": jnp.asarray(gl), "iscrowd": jnp.asarray(crowd)}]
        m_dev.update(p, t)
        m_np.update(p, t)
    r_dev, r_np = m_dev.compute(), m_np.compute()
    for k in r_np:
        np.testing.assert_allclose(
            np.asarray(r_dev[k]), np.asarray(r_np[k]), atol=1e-6, err_msg=k
        )


def test_backend_validation():
    with pytest.raises(ValueError, match="backend"):
        MeanAveragePrecision(backend="pycocotools")
