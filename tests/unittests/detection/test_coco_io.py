"""Native COCO json/RLE io: codec invariants + full metric round-trips."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.detection import MeanAveragePrecision
from torchmetrics_tpu.detection.coco_io import (
    _counts_from_string,
    _counts_to_string,
    ann_to_mask,
    rle_decode,
    rle_encode,
)


def test_rle_counts_string_roundtrip():
    rng = np.random.default_rng(0)
    for _ in range(20):
        counts = rng.integers(0, 5000, size=rng.integers(1, 40)).tolist()
        assert _counts_from_string(_counts_to_string(counts)) == counts


def test_rle_counts_known_values():
    # single run of 6 zeros: 6 fits in one 5-bit chunk -> chr(6+48) == '6'
    assert _counts_to_string([6]) == "6"
    assert _counts_from_string("6") == [6]
    # deltas from two back can be negative -> sign-extended chunks
    assert _counts_from_string(_counts_to_string([100, 5, 3, 90])) == [100, 5, 3, 90]


def test_rle_mask_roundtrip():
    rng = np.random.default_rng(1)
    for shape in [(4, 6), (11, 7), (1, 1), (16, 16)]:
        mask = rng.uniform(size=shape) > 0.6
        decoded = rle_decode(rle_encode(mask))
        np.testing.assert_array_equal(decoded, mask.astype(np.uint8))
        # uncompressed counts path too
        decoded_u = rle_decode(rle_encode(mask, compress=False))
        np.testing.assert_array_equal(decoded_u, mask.astype(np.uint8))
    # empty + full masks
    np.testing.assert_array_equal(rle_decode(rle_encode(np.zeros((3, 3), bool))), np.zeros((3, 3)))
    np.testing.assert_array_equal(rle_decode(rle_encode(np.ones((3, 3), bool))), np.ones((3, 3)))


def test_rle_decode_is_column_major():
    """COCO runs scan columns: a 1-run of length H fills the FIRST column."""
    rle = {"size": [3, 2], "counts": [0, 3, 3]}  # 3 ones then 3 zeros
    expected = np.asarray([[1, 0], [1, 0], [1, 0]], np.uint8)
    np.testing.assert_array_equal(rle_decode(rle), expected)


def test_ann_to_mask_polygon():
    pytest.importorskip("matplotlib")
    ann = {"segmentation": [[1.0, 1.0, 5.0, 1.0, 5.0, 5.0, 1.0, 5.0]]}  # 4x4 square
    mask = ann_to_mask(ann, 8, 8)
    assert mask[2, 2] == 1 and mask[0, 0] == 0 and mask[6, 6] == 0
    assert 9 <= mask.sum() <= 25  # ~16 modulo boundary rounding


def test_bbox_roundtrip_through_coco_files(tmp_path):
    """update -> tm_to_coco -> coco_to_tm -> update a fresh metric ->
    identical mAP results."""
    preds = [
        dict(boxes=jnp.asarray([[10.0, 20.0, 60.0, 80.0], [5.0, 5.0, 25.0, 30.0]]),
             scores=jnp.asarray([0.9, 0.4]), labels=jnp.asarray([0, 1])),
        dict(boxes=jnp.asarray([[0.0, 0.0, 40.0, 40.0]]),
             scores=jnp.asarray([0.7]), labels=jnp.asarray([1])),
    ]
    target = [
        dict(boxes=jnp.asarray([[12.0, 22.0, 58.0, 78.0]]), labels=jnp.asarray([0]),
             iscrowd=jnp.asarray([0])),
        dict(boxes=jnp.asarray([[2.0, 2.0, 38.0, 42.0], [50.0, 50.0, 90.0, 90.0]]),
             labels=jnp.asarray([1, 1]), iscrowd=jnp.asarray([0, 1])),
    ]
    metric = MeanAveragePrecision()
    metric.update(preds, target)
    expected = metric.compute()

    stem = str(tmp_path / "roundtrip")
    metric.tm_to_coco(stem)
    with open(f"{stem}_target.json") as handle:
        assert {"images", "annotations", "categories"} <= set(json.load(handle))

    loaded_preds, loaded_target = MeanAveragePrecision.coco_to_tm(
        f"{stem}_preds.json", f"{stem}_target.json", iou_type="bbox"
    )
    fresh = MeanAveragePrecision(box_format="xywh")  # coco files carry xywh
    fresh.update(loaded_preds, loaded_target)
    resumed = fresh.compute()
    for key in ("map", "map_50", "map_75", "mar_100", "map_small"):
        np.testing.assert_allclose(
            np.asarray(resumed[key]), np.asarray(expected[key]), atol=1e-6, err_msg=key
        )


def test_segm_roundtrip_through_coco_files(tmp_path):
    rng = np.random.default_rng(3)
    mask_gt = np.zeros((1, 20, 20), bool)
    mask_gt[0, 2:12, 3:13] = True
    mask_pred = np.zeros((1, 20, 20), bool)
    mask_pred[0, 2:12, 2:12] = True
    preds = [dict(masks=jnp.asarray(mask_pred), scores=jnp.asarray([0.8]), labels=jnp.asarray([3]))]
    target = [dict(masks=jnp.asarray(mask_gt), labels=jnp.asarray([3]))]

    metric = MeanAveragePrecision(iou_type="segm")
    metric.update(preds, target)
    expected = metric.compute()

    stem = str(tmp_path / "segm")
    metric.tm_to_coco(stem)
    loaded_preds, loaded_target = MeanAveragePrecision.coco_to_tm(
        f"{stem}_preds.json", f"{stem}_target.json", iou_type="segm"
    )
    fresh = MeanAveragePrecision(iou_type="segm")
    fresh.update(loaded_preds, loaded_target)
    resumed = fresh.compute()
    np.testing.assert_allclose(np.asarray(resumed["map"]), np.asarray(expected["map"]), atol=1e-6)
