"""CLIPScore / CLIP-IQA parity vs the reference with identical HF weights.

A tiny random-initialized torch CLIPModel + character-level CLIP BPE
tokenizer + processor are saved to a temp dir; the reference loads them with
torch (multimodal/clip_score.py:115-117), ours loads the same checkpoint
through FlaxCLIPModel(from_pt=True).  Same weights, same processor, same
inputs → scores must agree (VERDICT r2 "next" #2: the BERTScore hermetic
pattern applied to the last external-model family).

The text config must pin ``eos_token_id=1`` to match the tiny tokenizer —
CLIP text pooling selects the EOS position, and the default id (49407)
would silently pool BOS, collapsing every prompt to one embedding.
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # model-port heavy; deselect with -m 'not slow'

from tests.helpers.refpath import add_reference_paths

add_reference_paths()

transformers = pytest.importorskip("transformers")

PREDS_TEXT = ["a photo of a cat", "a red car", "a good dog"]


@pytest.fixture(scope="module")
def tiny_clip_dir(tmp_path_factory):
    import torch
    from transformers import (
        CLIPConfig,
        CLIPImageProcessor,
        CLIPModel,
        CLIPProcessor,
        CLIPTokenizer,
    )

    d = tmp_path_factory.mktemp("tiny_clip")
    # character-level CLIP BPE: every lowercase letter and its </w> form, no merges
    chars = sorted("abcdefghijklmnopqrstuvwxyz")
    vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1}
    for c in chars:
        vocab[c] = len(vocab)
        vocab[c + "</w>"] = len(vocab)
    (d / "vocab.json").write_text(json.dumps(vocab))
    (d / "merges.txt").write_text("#version: 0.2\n")
    tok = CLIPTokenizer(str(d / "vocab.json"), str(d / "merges.txt"), model_max_length=77)
    ip = CLIPImageProcessor(size={"shortest_edge": 32}, crop_size={"height": 32, "width": 32})
    CLIPProcessor(image_processor=ip, tokenizer=tok).save_pretrained(str(d))

    cfg = CLIPConfig(
        text_config=dict(
            vocab_size=len(vocab), hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2, max_position_embeddings=77,
            bos_token_id=0, eos_token_id=1,
        ),
        vision_config=dict(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=2, image_size=32, patch_size=8,
        ),
        projection_dim=16,
    )
    torch.manual_seed(0)
    CLIPModel(cfg).eval().save_pretrained(str(d))
    return str(d)


@pytest.fixture()
def images():
    rng = np.random.default_rng(42)
    return rng.integers(0, 255, (3, 3, 32, 32), dtype=np.uint8)


def test_tiny_clip_anchors_discriminate(tiny_clip_dir):
    """Guard against the degenerate-pooling failure mode: distinct prompts
    must embed distinctly, otherwise every comparison below is vacuous."""
    from torchmetrics_tpu.multimodal.backbones.clip import load_clip_encoders

    _, enc_t = load_clip_encoders(tiny_clip_dir)
    feats = np.asarray(enc_t(["Good photo.", "Bad photo."]))
    assert np.linalg.norm(feats[0] - feats[1]) > 0.1


def test_clip_score_reference_parity(tiny_clip_dir, images):
    import torch
    from torchmetrics.multimodal import CLIPScore as RefCLIPScore

    import jax.numpy as jnp
    from torchmetrics_tpu.multimodal import CLIPScore

    ref = RefCLIPScore(model_name_or_path=tiny_clip_dir)
    ours = CLIPScore(model_name_or_path=tiny_clip_dir)
    # batch-by-batch accumulation on both sides
    ref.update([torch.tensor(i) for i in images[:2]], PREDS_TEXT[:2])
    ref.update([torch.tensor(images[2])], PREDS_TEXT[2:])
    ours.update([jnp.asarray(i) for i in images[:2]], PREDS_TEXT[:2])
    ours.update([jnp.asarray(images[2])], PREDS_TEXT[2:])
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-3)


def test_clip_score_functional_parity(tiny_clip_dir, images):
    import torch
    from torchmetrics.functional.multimodal import clip_score as ref_clip_score

    import jax.numpy as jnp
    from torchmetrics_tpu.functional.multimodal import clip_score

    ref_val = ref_clip_score(
        [torch.tensor(i) for i in images], PREDS_TEXT, model_name_or_path=tiny_clip_dir
    )
    our_val = clip_score([jnp.asarray(i) for i in images], PREDS_TEXT, model_name_or_path=tiny_clip_dir)
    np.testing.assert_allclose(float(our_val), float(ref_val), atol=1e-3)


@pytest.mark.parametrize("prompts", [("quality",), ("quality", "brightness"), (("Super photo.", "Terrible photo."),)])
def test_clip_iqa_reference_parity(tiny_clip_dir, prompts):
    import torch
    from torchmetrics.functional.multimodal import (
        clip_image_quality_assessment as ref_iqa,
    )

    import jax.numpy as jnp
    from torchmetrics_tpu.functional.multimodal import clip_image_quality_assessment

    rng = np.random.default_rng(7)
    imgs = rng.integers(0, 255, (4, 3, 32, 32)).astype(np.float32)
    ref_val = ref_iqa(torch.tensor(imgs), model_name_or_path=tiny_clip_dir, data_range=255.0, prompts=prompts)
    our_val = clip_image_quality_assessment(
        jnp.asarray(imgs), model_name_or_path=tiny_clip_dir, data_range=255.0, prompts=prompts
    )
    if isinstance(ref_val, dict):
        assert set(our_val) == set(ref_val)
        for k in ref_val:
            np.testing.assert_allclose(np.asarray(our_val[k]), ref_val[k].numpy(), atol=1e-3)
    else:
        np.testing.assert_allclose(np.asarray(our_val), ref_val.numpy(), atol=1e-3)


def test_clip_iqa_modular_accumulation_parity(tiny_clip_dir):
    import torch
    from torchmetrics.functional.multimodal import (
        clip_image_quality_assessment as ref_iqa,
    )

    import jax.numpy as jnp
    from torchmetrics_tpu.multimodal import CLIPImageQualityAssessment

    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 255, (4, 3, 32, 32)).astype(np.float32)
    ref_val = ref_iqa(
        torch.tensor(imgs), model_name_or_path=tiny_clip_dir, data_range=255.0,
        prompts=("quality", "natural"),
    )
    m = CLIPImageQualityAssessment(
        model_name_or_path=tiny_clip_dir, data_range=255.0, prompts=("quality", "natural")
    )
    m.update(jnp.asarray(imgs[:2]))
    m.update(jnp.asarray(imgs[2:]))
    res = m.compute()
    for k in ref_val:
        np.testing.assert_allclose(np.asarray(res[k]), ref_val[k].numpy(), atol=1e-3)
