"""Multimodal metric tests with deterministic encoders."""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from torchmetrics_tpu.functional.multimodal import clip_image_quality_assessment, clip_score
from torchmetrics_tpu.functional.multimodal.clip_iqa import _clip_iqa_format_prompts
from torchmetrics_tpu.multimodal import CLIPImageQualityAssessment, CLIPScore


class AlignedImageEncoder:
    """Encodes an image by its mean channel intensities into a 3-dim embedding."""

    def __call__(self, images):
        return jnp.asarray(images).mean(axis=(2, 3))


class AlignedTextEncoder:
    """'red'/'green'/'blue' captions map to matching one-hot embeddings."""

    def __call__(self, text):
        table = {"red": [1.0, 0.0, 0.0], "green": [0.0, 1.0, 0.0], "blue": [0.0, 0.0, 1.0]}
        return jnp.asarray([table.get(t.split()[0].lower(), [0.5, 0.5, 0.5]) for t in text])


def _color_image(channel: int) -> jnp.ndarray:
    img = np.zeros((3, 8, 8), np.float32)
    img[channel] = 1.0
    return jnp.asarray(img)


def test_clip_score_alignment():
    imgs = [_color_image(0), _color_image(2)]
    good = float(clip_score(imgs, ["red", "blue"], image_encoder=AlignedImageEncoder(), text_encoder=AlignedTextEncoder()))
    bad = float(clip_score(imgs, ["blue", "red"], image_encoder=AlignedImageEncoder(), text_encoder=AlignedTextEncoder()))
    assert good == pytest.approx(100.0, abs=1e-3)
    assert bad == pytest.approx(0.0, abs=1e-3)


def test_clip_score_validation():
    with pytest.raises(ValueError, match="same"):
        clip_score([_color_image(0)], ["a", "b"])
    with pytest.raises(ValueError, match="3d"):
        clip_score([jnp.zeros((1, 3, 8, 8))], ["a"])


def test_clip_score_class_accumulation():
    m = CLIPScore(image_encoder=AlignedImageEncoder(), text_encoder=AlignedTextEncoder())
    m.update([_color_image(0)], ["red"])
    m.update([_color_image(1)], ["blue"])  # mismatch -> 0
    # mean of (100, 0) = 50
    assert float(m.compute()) == pytest.approx(50.0, abs=1e-3)


def test_clip_iqa_prompt_formatting():
    lst, names = _clip_iqa_format_prompts(("quality",))
    assert lst == ["Good photo.", "Bad photo."] and names == ["quality"]
    lst, names = _clip_iqa_format_prompts(("quality", ("Great pic.", "Awful pic.")))
    assert names == ["quality", "user_defined_0"]
    assert lst[2:] == ["Great pic.", "Awful pic."]
    with pytest.raises(ValueError, match="must be one of"):
        _clip_iqa_format_prompts(("bogus_keyword",))
    with pytest.raises(ValueError, match="length 2"):
        _clip_iqa_format_prompts((("a", "b", "c"),))


def test_clip_iqa_scores_in_unit_interval():
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.random((4, 3, 16, 16)), jnp.float32)
    out = clip_image_quality_assessment(imgs, prompts=("quality",))
    assert out.shape == (4,)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) <= 1)).all()
    out2 = clip_image_quality_assessment(imgs, prompts=("quality", "brightness"))
    assert set(out2) == {"quality", "brightness"}


def test_clip_iqa_anchor_preference():
    # anchor-aligned image must score near 1 for the positive prompt
    imgs = jnp.stack([_color_image(0), _color_image(2)])
    out = clip_image_quality_assessment(
        imgs,
        prompts=(("red", "blue"),),
        image_encoder=AlignedImageEncoder(),
        text_encoder=AlignedTextEncoder(),
    )
    assert float(out[0]) > 0.99  # red image prefers 'red' anchor
    assert float(out[1]) < 0.01  # blue image prefers 'blue' anchor


def test_clip_iqa_class():
    rng = np.random.default_rng(1)
    imgs = jnp.asarray(rng.random((4, 3, 16, 16)), jnp.float32)
    m = CLIPImageQualityAssessment(prompts=("quality",))
    m.update(imgs[:2])
    m.update(imgs[2:])
    out = m.compute()
    want = clip_image_quality_assessment(imgs, prompts=("quality",))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-6)


def test_clip_score_update_order_invariant():
    rng = np.random.default_rng(5)
    img_a = jnp.asarray(rng.random((3, 8, 8)), jnp.float32)
    img_b = jnp.asarray(rng.random((3, 8, 8)), jnp.float32)
    m1 = CLIPScore()
    m1.update([img_a], ["dog playing fetch"])
    m1.update([img_b], ["cat sleeping"])
    m2 = CLIPScore()
    m2.update([img_b], ["cat sleeping"])
    m2.update([img_a], ["dog playing fetch"])
    assert float(m1.compute()) == pytest.approx(float(m2.compute()), abs=1e-5)


def test_check_forward_full_state_property():
    from torchmetrics_tpu.utilities.checks import check_forward_full_state_property
    from torchmetrics_tpu import MeanSquaredError

    check_forward_full_state_property(
        MeanSquaredError,
        init_args={},
        input_args={"preds": jnp.asarray([1.0, 2.0]), "target": jnp.asarray([1.5, 2.5])},
        num_update_to_compare=3,
        reps=1,
    )
