"""Multimodal (CLIPScore) through the 8-device sharded-sync path.

Enrollment of the universal sharded tester for the multimodal domain
(VERDICT r4 next #2).  CLIPScore's states are (Σ score, n) sums; the test
injects array-based encoders so the image/text pairs are mesh-shardable
tensors (the real HF backbone path is covered by test_multimodal.py — the
sync contract is encoder-independent).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity

N = 8  # image/text pairs per step; one per device
H = W = 16
DIM = 12


def _image_encoder(images):
    # (B, 3, H, W) -> (B, DIM): fixed sinusoidal projection of channel means
    feats = images.mean(axis=(2, 3))  # (B, 3)
    freqs = jnp.arange(1, DIM + 1, dtype=jnp.float32)
    return jnp.sin(feats @ jnp.ones((3, DIM)) * freqs + feats[:, :1])


def _text_encoder(rows):
    return jnp.stack([jnp.asarray(r, jnp.float32) for r in rows])


def _make_metric():
    from torchmetrics_tpu.multimodal import CLIPScore

    class ArrayTextCLIPScore(CLIPScore):
        """CLIPScore whose captions are precomputed (B, DIM) embeddings, so
        every update input is a shardable tensor."""

        def _update(self, state, images, text_emb):
            return super()._update(state, images, list(text_emb))

    return ArrayTextCLIPScore(image_encoder=_image_encoder, text_encoder=_text_encoder)


@pytest.fixture()
def pairs():
    rng = np.random.default_rng(41)
    images = rng.uniform(size=(2, N, 3, H, W)).astype(np.float32)
    text_emb = rng.normal(size=(2, N, DIM)).astype(np.float32)
    return images, text_emb


def test_sharded_clip_score(mesh, pairs):
    images, text_emb = pairs
    batches = [(images[0], text_emb[0]), (images[1], text_emb[1])]

    # analytic oracle: mean of per-pair 100·cos clamped at 0 in compute
    img_f = np.asarray(_image_encoder(jnp.asarray(images.reshape(-1, 3, H, W))))
    img_f = img_f / np.linalg.norm(img_f, axis=-1, keepdims=True)
    txt_f = text_emb.reshape(-1, DIM) / np.linalg.norm(
        text_emb.reshape(-1, DIM), axis=-1, keepdims=True
    )
    oracle = max(float((100 * (img_f * txt_f).sum(-1)).mean()), 0.0)

    assert_sharded_parity(mesh, _make_metric, batches, oracle=oracle, atol=1e-3, rtol=1e-3)
