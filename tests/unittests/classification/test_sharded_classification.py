"""Classification tower through the 8-device sharded-sync path.

Enrollment of the universal sharded tester (tests/helpers/sharded.py) for
the classification domain: batch-split update over the mesh → in-graph sync
→ compute must equal single-device accumulation and the sklearn oracle
(the reference's own gold standard for this domain,
/root/reference/tests/unittests/classification/test_accuracy.py).
"""

import numpy as np
import pytest

from tests.helpers.sharded import assert_sharded_parity

NUM_CLASSES = 5
N = 64  # total rows; 8 devices x 2 steps x 4 rows


@pytest.fixture()
def probs_target():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(2, N, NUM_CLASSES)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    target = rng.integers(0, NUM_CLASSES, size=(2, N))
    return probs, target


def _batches(probs, target):
    return [(probs[0], target[0]), (probs[1], target[1])]


def test_sharded_multiclass_accuracy_micro(mesh, probs_target):
    from sklearn.metrics import accuracy_score

    from torchmetrics_tpu.classification import MulticlassAccuracy

    probs, target = probs_target
    oracle = accuracy_score(target.ravel(), probs.argmax(-1).ravel())
    assert_sharded_parity(
        mesh,
        lambda: MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
        _batches(probs, target),
        oracle=oracle,
    )


def test_sharded_multiclass_f1_macro(mesh, probs_target):
    from sklearn.metrics import f1_score

    from torchmetrics_tpu.classification import MulticlassF1Score

    probs, target = probs_target
    oracle = f1_score(target.ravel(), probs.argmax(-1).ravel(), average="macro")
    assert_sharded_parity(
        mesh,
        lambda: MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
        _batches(probs, target),
        oracle=oracle,
    )


def test_sharded_multiclass_auroc_binned(mesh, probs_target):
    from torchmetrics_tpu.classification import MulticlassAUROC

    probs, target = probs_target
    assert_sharded_parity(
        mesh,
        lambda: MulticlassAUROC(num_classes=NUM_CLASSES, thresholds=50, validate_args=False),
        _batches(probs, target),
    )


def test_sharded_multiclass_average_precision_cat_state(mesh, probs_target):
    """thresholds=None keeps raw cat states — exercises the all_gather leg."""
    from torchmetrics_tpu.classification import MulticlassAveragePrecision

    probs, target = probs_target
    assert_sharded_parity(
        mesh,
        lambda: MulticlassAveragePrecision(
            num_classes=NUM_CLASSES, thresholds=None, validate_args=False
        ),
        _batches(probs, target),
    )


def test_sharded_confusion_matrix(mesh, probs_target):
    from sklearn.metrics import confusion_matrix

    from torchmetrics_tpu.classification import MulticlassConfusionMatrix

    probs, target = probs_target
    oracle = confusion_matrix(
        target.ravel(), probs.argmax(-1).ravel(), labels=range(NUM_CLASSES)
    ).astype(np.float32)
    assert_sharded_parity(
        mesh,
        lambda: MulticlassConfusionMatrix(num_classes=NUM_CLASSES, validate_args=False),
        _batches(probs, target),
        oracle=oracle,
    )


def test_sharded_binary_accuracy(mesh):
    from torchmetrics_tpu.classification import BinaryAccuracy

    rng = np.random.default_rng(2)
    probs = rng.uniform(size=(2, N)).astype(np.float32)
    target = rng.integers(0, 2, size=(2, N))
    oracle = ((probs > 0.5).astype(int) == target).mean()
    assert_sharded_parity(
        mesh,
        lambda: BinaryAccuracy(validate_args=False),
        [(probs[0], target[0]), (probs[1], target[1])],
        oracle=oracle,
    )


def test_sharded_multilabel_f1(mesh):
    from sklearn.metrics import f1_score

    from torchmetrics_tpu.classification import MultilabelF1Score

    rng = np.random.default_rng(3)
    probs = rng.uniform(size=(2, N, 4)).astype(np.float32)
    target = rng.integers(0, 2, size=(2, N, 4))
    oracle = f1_score(
        target.reshape(-1, 4), (probs > 0.5).astype(int).reshape(-1, 4), average="macro",
        zero_division=0,
    )
    assert_sharded_parity(
        mesh,
        lambda: MultilabelF1Score(num_labels=4, average="macro", validate_args=False),
        [(probs[0], target[0]), (probs[1], target[1])],
        oracle=oracle,
    )
