"""Dice flexible-input parity vs the ACTUAL reference Dice.

VERDICT r4 next #8: ``classify_inputs`` (the port of the reference's
796-line ``_input_format_classification`` machinery) must have a real
consumer.  Dice is the reference's legacy-style entry point that accepts
every classification input layout; these tests feed the same heterogeneous
inputs to the reference Dice (which canonicalizes via
``_input_format_classification``) and ours (via ``classify_inputs``) and
require identical scores.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.helpers.refpath import require_reference

require_reference()

import torch  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from torchmetrics_tpu.classification import Dice  # noqa: E402

N = 24


def _both(ours_kwargs, ref_kwargs, preds, target):
    from torchmetrics.classification import Dice as RefDice

    ref = RefDice(**ref_kwargs)
    ref.update(torch.tensor(np.asarray(preds)), torch.tensor(np.asarray(target)))
    ours = Dice(**ours_kwargs)
    ours.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(
        np.asarray(ours.compute(), np.float64), float(ref.compute()), atol=1e-6
    )


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_dice_int_labels(average):
    rng = np.random.default_rng(0)
    preds = rng.integers(0, 3, N)
    target = rng.integers(0, 3, N)
    _both(
        dict(num_classes=3, average=average),
        dict(num_classes=3, average=average),
        preds,
        target,
    )


def test_dice_probs_matrix():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(N, 3)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    target = rng.integers(0, 3, N)
    _both(dict(num_classes=3, average="micro"), dict(num_classes=3, average="micro"), probs, target)


def test_dice_binary_float_promoted():
    rng = np.random.default_rng(2)
    probs = rng.uniform(size=N).astype(np.float32)
    target = rng.integers(0, 2, N)
    _both(
        dict(num_classes=2, average="micro", multiclass=True),
        dict(num_classes=2, average="micro", multiclass=True),
        probs,
        target,
    )


def test_dice_multidim_labels():
    rng = np.random.default_rng(3)
    preds = rng.integers(0, 3, (N, 5))
    target = rng.integers(0, 3, (N, 5))
    _both(dict(num_classes=3, average="micro"), dict(num_classes=3, average="micro"), preds, target)


def test_dice_ignore_index():
    rng = np.random.default_rng(4)
    preds = rng.integers(0, 3, N)
    target = rng.integers(0, 3, N)
    target[:4] = 1
    _both(
        dict(num_classes=3, average="micro", ignore_index=1),
        dict(num_classes=3, average="micro", ignore_index=1),
        preds,
        target,
    )


def test_dice_binary_without_multiclass_raises_like_reference():
    """Both implementations demand an explicit multiclass=True for binary
    data viewed as two classes."""
    from torchmetrics.classification import Dice as RefDice

    with pytest.raises(ValueError, match="multiclass"):
        RefDice(num_classes=2).update(torch.tensor([0.9, 0.2]), torch.tensor([1, 0]))
    with pytest.raises(ValueError, match="multiclass"):
        Dice(num_classes=2).update(jnp.asarray([0.9, 0.2]), jnp.asarray([1, 0]))


def test_dice_rejects_class_count_mismatch():
    with pytest.raises(ValueError):  # classify_inputs rejects binary num_classes>2 loudly
        m = Dice(num_classes=4)
        m.update(jnp.asarray([0.1, 0.8, 0.4]), jnp.asarray([0, 1, 1]))


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_dice_ignore_index_macro(average):
    rng = np.random.default_rng(5)
    preds = rng.integers(0, 4, N)
    target = rng.integers(0, 4, N)
    _both(
        dict(num_classes=4, average=average, ignore_index=2),
        dict(num_classes=4, average=average, ignore_index=2),
        preds,
        target,
    )
