"""Fixed-operating-point family tests vs brute-force numpy references."""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from torchmetrics_tpu.classification import (
    BinaryPrecisionAtFixedRecall,
    BinaryRecallAtFixedPrecision,
    BinarySensitivityAtSpecificity,
    BinarySpecificityAtSensitivity,
    MulticlassRecallAtFixedPrecision,
    MultilabelRecallAtFixedPrecision,
    PrecisionAtFixedRecall,
    RecallAtFixedPrecision,
    SensitivityAtSpecificity,
    SpecificityAtSensitivity,
)
from torchmetrics_tpu.functional.classification import (
    binary_precision_at_fixed_recall,
    binary_recall_at_fixed_precision,
    binary_sensitivity_at_specificity,
    binary_specificity_at_sensitivity,
    multiclass_recall_at_fixed_precision,
)

SEED = 0


def _brute_force(preds, target, min_constraint, objective, constraint):
    """Scan all prediction thresholds; return best objective value."""
    best = 0.0
    for thr in np.unique(preds):
        hard = (preds >= thr).astype(int)
        tp = ((hard == 1) & (target == 1)).sum()
        fp = ((hard == 1) & (target == 0)).sum()
        fn = ((hard == 0) & (target == 1)).sum()
        tn = ((hard == 0) & (target == 0)).sum()
        stats = {
            "precision": tp / (tp + fp) if tp + fp else 1.0,
            "recall": tp / (tp + fn) if tp + fn else 0.0,
            "specificity": tn / (tn + fp) if tn + fp else 0.0,
        }
        if stats[constraint] >= min_constraint:
            best = max(best, stats[objective])
    return best


@pytest.mark.parametrize("min_val", [0.2, 0.5, 0.8])
@pytest.mark.parametrize(
    "fn,objective,constraint",
    [
        (binary_precision_at_fixed_recall, "precision", "recall"),
        (binary_recall_at_fixed_precision, "recall", "precision"),
        (binary_sensitivity_at_specificity, "recall", "specificity"),
        (binary_specificity_at_sensitivity, "specificity", "recall"),
    ],
)
def test_binary_functional_vs_brute_force(fn, objective, constraint, min_val):
    rng = np.random.default_rng(SEED)
    preds = rng.random(200)
    target = rng.integers(0, 2, 200)
    got, thr = fn(jnp.asarray(preds), jnp.asarray(target), min_val)
    want = _brute_force(preds, target, min_val, objective, constraint)
    assert float(got) == pytest.approx(want, abs=1e-6)


def test_binned_close_to_exact():
    rng = np.random.default_rng(SEED)
    preds = rng.random(500)
    target = rng.integers(0, 2, 500)
    exact, _ = binary_recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), 0.52)
    binned, _ = binary_recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), 0.52, thresholds=200)
    assert float(binned) == pytest.approx(float(exact), abs=0.05)


def test_multiclass_per_class_shapes():
    rng = np.random.default_rng(SEED)
    preds = rng.random((100, 4))
    preds = preds / preds.sum(1, keepdims=True)
    target = rng.integers(0, 4, 100)
    vals, thrs = multiclass_recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), 4, 0.3)
    assert vals.shape == (4,) and thrs.shape == (4,)
    assert ((np.asarray(vals) >= 0) & (np.asarray(vals) <= 1)).all()


def test_class_api_matches_functional():
    rng = np.random.default_rng(SEED)
    preds = rng.random(150)
    target = rng.integers(0, 2, 150)
    m = BinaryRecallAtFixedPrecision(min_value=0.6)
    m.update(jnp.asarray(preds[:75]), jnp.asarray(target[:75]))
    m.update(jnp.asarray(preds[75:]), jnp.asarray(target[75:]))
    got_v, got_t = m.compute()
    want_v, want_t = binary_recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), 0.6)
    assert float(got_v) == pytest.approx(float(want_v), abs=1e-6)
    assert float(got_t) == pytest.approx(float(want_t), abs=1e-6)


def test_task_wrappers_dispatch():
    assert type(PrecisionAtFixedRecall(task="binary", min_recall=0.5)).__name__ == "BinaryPrecisionAtFixedRecall"
    assert type(RecallAtFixedPrecision(task="multiclass", min_precision=0.5, num_classes=3)).__name__ == "MulticlassRecallAtFixedPrecision"
    assert type(SensitivityAtSpecificity(task="multilabel", min_specificity=0.5, num_labels=3)).__name__ == "MultilabelSensitivityAtSpecificity"
    assert type(SpecificityAtSensitivity(task="binary", min_sensitivity=0.5)).__name__ == "BinarySpecificityAtSensitivity"
    with pytest.raises(ValueError, match="not supported"):
        PrecisionAtFixedRecall(task="bogus", min_recall=0.5)


def test_no_valid_point_fallback():
    # impossible precision constraint => (0, 1e6)
    preds = jnp.asarray([0.1, 0.2, 0.3, 0.4])
    target = jnp.asarray([0, 0, 0, 0])
    v, t = binary_recall_at_fixed_precision(preds, target, 0.9)
    assert float(v) == 0.0
    assert float(t) == pytest.approx(1e6)


def test_roc_family_keeps_real_threshold():
    # the ROC origin (spec=1, tpr=0, thr=1.0) satisfies the constraint ->
    # real threshold returned, not the 1e6 sentinel (reference
    # sensitivity_specificity.py only sentinels when nothing satisfies)
    v, t = binary_sensitivity_at_specificity(
        jnp.asarray([0.2, 0.8]), jnp.asarray([1, 0]), 0.5
    )
    assert float(v) == 0.0
    assert float(t) <= 1.0


def test_int_min_values_accepted():
    preds = jnp.asarray([0.1, 0.9])
    target = jnp.asarray([0, 1])
    v, _ = binary_precision_at_fixed_recall(preds, target, 1)
    assert float(v) == pytest.approx(1.0)
    v2, _ = binary_recall_at_fixed_precision(preds, target, 0)
    assert float(v2) == pytest.approx(1.0)


def test_min_value_validation():
    with pytest.raises(ValueError, match="min_precision"):
        binary_recall_at_fixed_precision(jnp.zeros(4), jnp.zeros(4, jnp.int32), 1.5)
    with pytest.raises(ValueError, match="min_recall"):
        BinaryPrecisionAtFixedRecall(min_value=-0.1)


def test_multilabel_class():
    rng = np.random.default_rng(SEED)
    preds = rng.random((60, 3))
    target = rng.integers(0, 2, (60, 3))
    m = MultilabelRecallAtFixedPrecision(num_labels=3, min_value=0.4)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    vals, thrs = m.compute()
    assert vals.shape == (3,)
    m2 = MulticlassRecallAtFixedPrecision(num_classes=3, min_value=0.4, thresholds=50)
    probs = rng.random((60, 3))
    probs = probs / probs.sum(1, keepdims=True)
    m2.update(jnp.asarray(probs), jnp.asarray(rng.integers(0, 3, 60)))
    vals2, _ = m2.compute()
    assert vals2.shape == (3,)
