"""MulticlassExactMatch ignore_index parity: the modular metric's global
mean must match the functional path when ``ignore_index`` leaves some
samples fully ignored — those samples must not dilute the denominator."""

import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.classification import MulticlassExactMatch
from torchmetrics_tpu.functional.classification import multiclass_exact_match

# sample 0 matches everywhere, sample 1 mismatches at a non-ignored slot,
# sample 2 is ENTIRELY ignore_index — only 2 samples should count
PREDS = jnp.asarray([[0, 1, 2], [2, 1, 0], [1, 1, 1]])
TARGET = jnp.asarray([[0, 1, 2], [2, 0, 0], [-1, -1, -1]])


def test_global_mean_ignores_fully_masked_samples():
    fn = multiclass_exact_match(PREDS, TARGET, num_classes=3, ignore_index=-1)
    m = MulticlassExactMatch(num_classes=3, ignore_index=-1)
    m.update(PREDS, TARGET)
    assert float(fn) == pytest.approx(0.5)
    assert float(m.compute()) == pytest.approx(float(fn))


def test_partially_ignored_positions_still_match():
    # sample 1's mismatch sits at an IGNORED slot: the sample counts as a match
    target = jnp.asarray([[0, 1, 2], [2, -1, 0], [-1, -1, -1]])
    fn = multiclass_exact_match(PREDS, target, num_classes=3, ignore_index=-1)
    m = MulticlassExactMatch(num_classes=3, ignore_index=-1)
    m.update(PREDS, target)
    assert float(fn) == pytest.approx(1.0)
    assert float(m.compute()) == pytest.approx(1.0)


def test_modular_functional_parity_across_batches():
    rng = np.random.default_rng(3)
    preds = rng.integers(0, 4, size=(3, 16, 5))
    target = rng.integers(0, 4, size=(3, 16, 5))
    target[rng.random(target.shape) < 0.3] = -1
    target[0, 0] = -1  # force one fully-ignored sample
    m = MulticlassExactMatch(num_classes=4, ignore_index=-1)
    for step in range(3):
        m.update(jnp.asarray(preds[step]), jnp.asarray(target[step]))
    fn = multiclass_exact_match(
        jnp.asarray(preds.reshape(-1, 5)), jnp.asarray(target.reshape(-1, 5)),
        num_classes=4, ignore_index=-1,
    )
    np.testing.assert_allclose(float(m.compute()), float(fn), rtol=1e-6)


def test_samplewise_unchanged():
    m = MulticlassExactMatch(num_classes=3, ignore_index=-1, multidim_average="samplewise")
    m.update(PREDS, TARGET)
    out = np.asarray(m.compute())
    np.testing.assert_allclose(out, [1.0, 0.0, 0.0])
