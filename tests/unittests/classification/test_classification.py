"""Classification tower tests vs sklearn (reference test strategy: SURVEY.md §4)."""

import numpy as np
import jax.numpy as jnp
import pytest
from sklearn import metrics as skm

from tests.helpers.testers import run_class_metric_test, run_functional_metric_test

from torchmetrics_tpu.classification import (
    AUROC,
    Accuracy,
    BinaryAccuracy,
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryCalibrationError,
    BinaryCohenKappa,
    BinaryConfusionMatrix,
    BinaryF1Score,
    BinaryFairness,
    BinaryHingeLoss,
    BinaryJaccardIndex,
    BinaryMatthewsCorrCoef,
    BinaryPrecision,
    BinaryPrecisionRecallCurve,
    BinaryRecall,
    BinaryROC,
    BinarySpecificity,
    BinaryStatScores,
    Dice,
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MulticlassCalibrationError,
    MulticlassCohenKappa,
    MulticlassConfusionMatrix,
    MulticlassExactMatch,
    MulticlassF1Score,
    MulticlassHingeLoss,
    MulticlassJaccardIndex,
    MulticlassMatthewsCorrCoef,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    MultilabelCoverageError,
    MultilabelExactMatch,
    MultilabelF1Score,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
)
from torchmetrics_tpu.functional.classification import (
    binary_calibration_error,
    multiclass_exact_match,
    multilabel_exact_match,
    multilabel_coverage_error,
    multilabel_ranking_average_precision,
    multilabel_ranking_loss,
)

N_BATCHES, BATCH, C, L = 4, 32, 5, 4
rng = np.random.default_rng(7)

MC_TARGET = rng.integers(0, C, (N_BATCHES, BATCH))
MC_LOGITS = rng.normal(size=(N_BATCHES, BATCH, C)).astype(np.float32)
MC_PROBS = np.exp(MC_LOGITS) / np.exp(MC_LOGITS).sum(-1, keepdims=True)
MC_PREDS = MC_PROBS.argmax(-1)

BIN_TARGET = rng.integers(0, 2, (N_BATCHES, BATCH))
BIN_PROBS = np.round(rng.random((N_BATCHES, BATCH)), 2).astype(np.float32)  # with ties
BIN_PREDS = (BIN_PROBS > 0.5).astype(int)

ML_TARGET = rng.integers(0, 2, (N_BATCHES, BATCH, L))
ML_PROBS = rng.random((N_BATCHES, BATCH, L)).astype(np.float32)
ML_PREDS = (ML_PROBS > 0.5).astype(int)


def _flat(x):
    return x.reshape((-1,) + x.shape[2:])


# ------------------------------------------------------------------ binary
@pytest.mark.parametrize("factory,ref", [
    (lambda: BinaryAccuracy(), lambda p, t: skm.accuracy_score(t, p > 0.5)),
    (lambda: BinaryPrecision(), lambda p, t: skm.precision_score(t, p > 0.5)),
    (lambda: BinaryRecall(), lambda p, t: skm.recall_score(t, p > 0.5)),
    (lambda: BinaryF1Score(), lambda p, t: skm.f1_score(t, p > 0.5)),
    (lambda: BinarySpecificity(), lambda p, t: skm.recall_score(1 - t, ~(p > 0.5))),
    (lambda: BinaryCohenKappa(), lambda p, t: skm.cohen_kappa_score(t, p > 0.5)),
    (lambda: BinaryMatthewsCorrCoef(), lambda p, t: skm.matthews_corrcoef(t, p > 0.5)),
    (lambda: BinaryJaccardIndex(), lambda p, t: skm.jaccard_score(t, p > 0.5)),
    (lambda: BinaryConfusionMatrix(), lambda p, t: skm.confusion_matrix(t, p > 0.5)),
    (lambda: BinaryAUROC(), lambda p, t: skm.roc_auc_score(t, p)),
    (lambda: BinaryAveragePrecision(), lambda p, t: skm.average_precision_score(t, p)),
])
def test_binary_metrics_vs_sklearn(factory, ref):
    run_class_metric_test(factory, BIN_PROBS, BIN_TARGET, ref)


def test_binary_stat_scores():
    def ref(p, t):
        pl = (p > 0.5).astype(int)
        tp = ((pl == 1) & (t == 1)).sum()
        fp = ((pl == 1) & (t == 0)).sum()
        tn = ((pl == 0) & (t == 0)).sum()
        fn = ((pl == 0) & (t == 1)).sum()
        return np.array([tp, fp, tn, fn, tp + fn])

    run_class_metric_test(lambda: BinaryStatScores(), BIN_PROBS, BIN_TARGET, ref)


def test_binary_roc_binned_sane():
    m = BinaryROC(thresholds=20)
    for i in range(N_BATCHES):
        m.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
    fpr, tpr, thr = m.compute()
    assert fpr.shape == (20,) and tpr.shape == (20,)
    assert bool(jnp.all(jnp.diff(fpr) >= -1e-7)) and bool(jnp.all(jnp.diff(tpr) >= -1e-7))


def test_binary_prc_binned_close_to_exact():
    exact, binned = BinaryAveragePrecision(), BinaryAveragePrecision(thresholds=500)
    for i in range(N_BATCHES):
        exact.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
        binned.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
    np.testing.assert_allclose(float(exact.compute()), float(binned.compute()), atol=5e-3)


# ------------------------------------------------------------------ multiclass
@pytest.mark.parametrize("average,sk_average", [("micro", "micro"), ("macro", "macro"), ("weighted", "weighted"), ("none", None)])
def test_multiclass_f1_averages(average, sk_average):
    run_class_metric_test(
        lambda: MulticlassF1Score(num_classes=C, average=average),
        MC_PROBS, MC_TARGET,
        lambda p, t: skm.f1_score(t, p.argmax(-1), average=sk_average, labels=range(C)),
    )


@pytest.mark.parametrize("factory,ref", [
    (lambda: MulticlassAccuracy(num_classes=C, average="micro"), lambda p, t: skm.accuracy_score(t, p.argmax(-1))),
    (lambda: MulticlassPrecision(num_classes=C, average="macro"), lambda p, t: skm.precision_score(t, p.argmax(-1), average="macro")),
    (lambda: MulticlassRecall(num_classes=C, average="weighted"), lambda p, t: skm.recall_score(t, p.argmax(-1), average="weighted")),
    (lambda: MulticlassCohenKappa(num_classes=C), lambda p, t: skm.cohen_kappa_score(t, p.argmax(-1))),
    (lambda: MulticlassMatthewsCorrCoef(num_classes=C), lambda p, t: skm.matthews_corrcoef(t, p.argmax(-1))),
    (lambda: MulticlassJaccardIndex(num_classes=C), lambda p, t: skm.jaccard_score(t, p.argmax(-1), average="macro")),
    (lambda: MulticlassConfusionMatrix(num_classes=C), lambda p, t: skm.confusion_matrix(t, p.argmax(-1))),
    (lambda: MulticlassAUROC(num_classes=C), lambda p, t: skm.roc_auc_score(t, p, multi_class="ovr", average="macro")),
    (lambda: MulticlassAveragePrecision(num_classes=C), lambda p, t: np.mean([
        skm.average_precision_score((t == c).astype(int), p[:, c]) for c in range(C)
    ])),
])
def test_multiclass_metrics_vs_sklearn(factory, ref):
    run_class_metric_test(factory, MC_PROBS, MC_TARGET, ref)


def test_multiclass_accuracy_topk():
    run_class_metric_test(
        lambda: MulticlassAccuracy(num_classes=C, average="micro", top_k=2),
        MC_PROBS, MC_TARGET,
        lambda p, t: skm.top_k_accuracy_score(t, p, k=2, labels=range(C)),
    )


def test_multiclass_ignore_index():
    t2 = MC_TARGET.copy()
    t2[:, :5] = -1
    m = MulticlassAccuracy(num_classes=C, average="micro", ignore_index=-1)
    for i in range(N_BATCHES):
        m.update(jnp.asarray(MC_PROBS[i]), jnp.asarray(t2[i]))
    expected = skm.accuracy_score(_flat(MC_TARGET[:, 5:]), _flat(MC_PREDS[:, 5:]))
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_multiclass_exact_match():
    t = rng.integers(0, C, (8, 16))
    p = rng.integers(0, C, (8, 16))
    res = multiclass_exact_match(jnp.asarray(p), jnp.asarray(t), C)
    expected = np.mean([(p[i] == t[i]).all() for i in range(8)])
    np.testing.assert_allclose(float(res), expected)


def test_multiclass_samplewise():
    m = MulticlassAccuracy(num_classes=C, average="micro", multidim_average="samplewise")
    t = rng.integers(0, C, (2, 8, 6))
    p = rng.integers(0, C, (2, 8, 6))
    for i in range(2):
        m.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    res = np.asarray(m.compute())
    expected = np.concatenate([(p[i] == t[i]).mean(-1) for i in range(2)])
    np.testing.assert_allclose(res, expected, atol=1e-6)


# ------------------------------------------------------------------ multilabel
@pytest.mark.parametrize("factory,ref", [
    (lambda: MultilabelAccuracy(num_labels=L, average="macro"), lambda p, t: np.mean([
        skm.accuracy_score(t[:, i], p[:, i] > 0.5) for i in range(L)
    ])),
    (lambda: MultilabelF1Score(num_labels=L, average="macro"), lambda p, t: skm.f1_score(t, p > 0.5, average="macro")),
])
def test_multilabel_metrics_vs_sklearn(factory, ref):
    run_class_metric_test(factory, ML_PROBS, ML_TARGET, ref)


def test_multilabel_exact_match():
    res = multilabel_exact_match(jnp.asarray(_flat(ML_PROBS)), jnp.asarray(_flat(ML_TARGET)), L)
    expected = np.mean([(row_p == row_t).all() for row_p, row_t in zip(_flat(ML_PREDS), _flat(ML_TARGET))])
    np.testing.assert_allclose(float(res), expected)


# ------------------------------------------------------------------ ranking
def test_ranking_vs_sklearn():
    p, t = _flat(ML_PROBS), _flat(ML_TARGET)
    np.testing.assert_allclose(
        float(multilabel_coverage_error(jnp.asarray(p), jnp.asarray(t), L)),
        skm.coverage_error(t, p), atol=1e-5)
    np.testing.assert_allclose(
        float(multilabel_ranking_average_precision(jnp.asarray(p), jnp.asarray(t), L)),
        skm.label_ranking_average_precision_score(t, p), atol=1e-5)
    np.testing.assert_allclose(
        float(multilabel_ranking_loss(jnp.asarray(p), jnp.asarray(t), L)),
        skm.label_ranking_loss(t, p), atol=1e-5)


def test_ranking_classes():
    for cls, fn in [
        (MultilabelCoverageError, skm.coverage_error),
        (MultilabelRankingAveragePrecision, skm.label_ranking_average_precision_score),
        (MultilabelRankingLoss, skm.label_ranking_loss),
    ]:
        m = cls(num_labels=L)
        for i in range(N_BATCHES):
            m.update(jnp.asarray(ML_PROBS[i]), jnp.asarray(ML_TARGET[i]))
        # mean of per-batch values (batch-weighted), matches reference accumulation
        expected = np.mean([fn(ML_TARGET[i], ML_PROBS[i]) for i in range(N_BATCHES)])
        np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


# ------------------------------------------------------------------ calibration / hinge
def test_binary_calibration_error():
    p, t = _flat(BIN_PROBS), _flat(BIN_TARGET)
    res = binary_calibration_error(jnp.asarray(p), jnp.asarray(t), n_bins=10, norm="l1")
    # manual ECE with the reference's binary convention: confidence is the
    # positive-class probability, accuracy is the target
    # (reference calibration_error.py:136-138); bin 10 holds conf == 1.0
    conf = p
    acc = t.astype(np.float64)
    bins = np.clip((conf * 10).astype(int), 0, 10)
    ece = 0.0
    for b in range(11):
        mask = bins == b
        if mask.sum():
            ece += np.abs(acc[mask].mean() - conf[mask].mean()) * mask.mean()
    np.testing.assert_allclose(float(res), ece, atol=1e-6)


def test_calibration_error_class_accumulation():
    m = BinaryCalibrationError(n_bins=10)
    for i in range(N_BATCHES):
        m.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
    f = binary_calibration_error(jnp.asarray(_flat(BIN_PROBS)), jnp.asarray(_flat(BIN_TARGET)), n_bins=10)
    np.testing.assert_allclose(float(m.compute()), float(f), atol=1e-6)


def test_hinge_loss():
    m = MulticlassHingeLoss(num_classes=C)
    for i in range(N_BATCHES):
        m.update(jnp.asarray(MC_PROBS[i]), jnp.asarray(MC_TARGET[i]))
    p, t = _flat(MC_PROBS), _flat(MC_TARGET)
    ts = p[np.arange(len(t)), t]
    other = p.copy()
    other[np.arange(len(t)), t] = -np.inf
    margin = ts - other.max(-1)
    expected = np.maximum(1 - margin, 0).mean()
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


# ------------------------------------------------------------------ task dispatch + misc
def test_task_dispatch_returns_subclass():
    m = Accuracy(task="multiclass", num_classes=C)
    assert type(m).__name__ == "MulticlassAccuracy"
    m = Accuracy(task="binary")
    assert type(m).__name__ == "BinaryAccuracy"
    m = AUROC(task="binary")
    assert type(m).__name__ == "BinaryAUROC"
    with pytest.raises(ValueError, match="not supported"):
        Accuracy(task="bogus")


def test_dice():
    m = Dice(num_classes=C, average="micro")
    for i in range(N_BATCHES):
        m.update(jnp.asarray(MC_PREDS[i]), jnp.asarray(MC_TARGET[i]))
    expected = skm.f1_score(_flat(MC_TARGET), _flat(MC_PREDS), average="micro")
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_binary_fairness():
    m = BinaryFairness(num_groups=2)
    preds = jnp.asarray(_flat(BIN_PROBS))
    target = jnp.asarray(_flat(BIN_TARGET))
    groups = jnp.asarray(rng.integers(0, 2, preds.shape[0]))
    m.update(preds, target, groups)
    out = m.compute()
    assert any(k.startswith("DP") for k in out) and any(k.startswith("EO") for k in out)
    for v in out.values():
        assert 0 <= float(v) <= 1.0 + 1e-6


# ------------------------------------------------------------------ review regressions
def test_multiclass_prc_multidim_layout():
    """(N, C, S) inputs must pair spatial positions with their class scores."""
    from torchmetrics_tpu.functional.classification import multiclass_average_precision

    p = rng.random((6, 3, 4)).astype(np.float32)
    p = p / p.sum(1, keepdims=True)
    t = rng.integers(0, 3, (6, 4))
    res = multiclass_average_precision(jnp.asarray(p), jnp.asarray(t), 3, average="macro")
    p_flat = np.moveaxis(p, 1, -1).reshape(-1, 3)
    t_flat = t.reshape(-1)
    expected = np.mean([skm.average_precision_score((t_flat == c).astype(int), p_flat[:, c]) for c in range(3)])
    np.testing.assert_allclose(float(res), expected, atol=1e-5)


def test_macro_topk_weighting():
    """With top_k > 1, classes absent from target (tp+fn==0) are excluded from macro."""
    from torchmetrics_tpu.functional.classification import multiclass_accuracy

    # class 2 never in target but often in top-2 preds
    t = np.array([0, 1, 0, 1])
    p = np.array([[0.5, 0.2, 0.3], [0.2, 0.5, 0.3], [0.5, 0.2, 0.3], [0.2, 0.5, 0.3]], dtype=np.float32)
    res = multiclass_accuracy(jnp.asarray(p), jnp.asarray(t), 3, average="macro", top_k=2)
    np.testing.assert_allclose(float(res), 1.0)  # classes 0,1 perfect; class 2 excluded


def test_jaccard_ignore_index_excluded_from_macro():
    t = np.array([0, 0, 1, 1, 2, 2])
    p = np.array([0, 0, 1, 1, 0, 1])  # class-2 preds hit 0/1
    res = MulticlassJaccardIndex(num_classes=3, average="macro", ignore_index=2)
    res.update(jnp.asarray(p), jnp.asarray(t))
    # class 2 rows dropped; remaining: t=[0,0,1,1] p=[0,0,1,1] -> classes 0,1 perfect
    np.testing.assert_allclose(float(res.compute()), 1.0)


def test_coverage_error_ignore_index():
    t = np.array([[1, 0, -1], [0, 1, -1]])
    p = np.array([[0.9, 0.1, 0.95], [0.2, 0.8, 0.99]], dtype=np.float32)
    res = multilabel_coverage_error(jnp.asarray(p), jnp.asarray(t), 3, ignore_index=-1)
    # ignored label must not count toward coverage: both samples cover at rank 1
    np.testing.assert_allclose(float(res), 1.0)


def test_confmat_validate_args():
    from torchmetrics_tpu.functional.classification import multiclass_confusion_matrix

    with pytest.raises(ValueError, match="normalize"):
        multiclass_confusion_matrix(jnp.asarray([0]), jnp.asarray([0]), 2, normalize="bogus")
    with pytest.raises(ValueError, match="num_classes"):
        multiclass_confusion_matrix(jnp.asarray([0]), jnp.asarray([0]), 0)


def test_exact_match_class():
    m = MulticlassExactMatch(num_classes=C)
    t = rng.integers(0, C, (2, 8, 6))
    p = rng.integers(0, C, (2, 8, 6))
    for i in range(2):
        m.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    expected = np.mean([(p[i, j] == t[i, j]).all() for i in range(2) for j in range(8)])
    np.testing.assert_allclose(float(m.compute()), expected)
