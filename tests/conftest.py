"""Test session config: force an 8-device virtual CPU mesh BEFORE jax backend init.

Mirrors the reference's persistent 2-process gloo pool
(/root/reference/tests/unittests/conftest.py:62-68) — but JAX needs no
processes: ``--xla_force_host_platform_device_count=8`` gives 8 local CPU
devices, and shard_map over a Mesh exercises the exact collective code paths
that run over ICI on a real pod slice.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Opt-in persistent compile cache for the hourly dev loop: the suite is
# JIT-dominated (~full-run compiles dwarf the math), and a warm cache cuts
# wall time substantially.  Off by default — XLA:CPU AOT reload warns about
# machine-feature mismatches that could SIGILL on a different host, so only
# same-machine rerun loops should enable it.
if os.environ.get("TM_TPU_JIT_CACHE"):
    cache_dir = os.environ.get("TM_TPU_JIT_CACHE_DIR", "/tmp/tm_tpu_jit_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

NUM_DEVICES = 8
SEED = 42


@pytest.fixture(scope="session")
def mesh():
    from jax.sharding import Mesh

    devices = jax.devices()
    assert len(devices) == NUM_DEVICES, f"expected {NUM_DEVICES} virtual devices, got {len(devices)}"
    return Mesh(np.asarray(devices).reshape(NUM_DEVICES), ("data",))


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(SEED)
