"""Regenerate recorded external-oracle fixtures.

Runs wherever the external tools exist (``pip install pycocotools pystoi``)
and rewrites the committed JSON vectors from seeded, deterministic inputs.
In an image without the tools it reports which fixtures stay ``pending``.

Usage::

    python tests/fixtures/generate_fixtures.py          # dry run: report
    python tests/fixtures/generate_fixtures.py --write  # rewrite fixtures
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


# ------------------------------------------------------------ deterministic inputs
def stoi_signals():
    """Three seeded 1-second 10 kHz signals with distinct degradation levels."""
    rng = np.random.default_rng(1234)
    fs = 10000
    t = np.arange(fs) / fs
    clean = (
        np.sin(2 * np.pi * 180 * t) * (1 + 0.6 * np.sin(2 * np.pi * 3.5 * t))
        + 0.4 * np.sin(2 * np.pi * 370 * t) * (1 + 0.5 * np.sin(2 * np.pi * 6 * t))
    ).astype(np.float64)
    cases = {}
    for name, snr_db in (("light_noise_10db", 10.0), ("heavy_noise_0db", 0.0), ("severe_noise_m5db", -5.0)):
        noise = rng.normal(size=fs)
        noise *= np.sqrt((clean**2).sum() / (noise**2).sum()) * 10 ** (-snr_db / 20)
        cases[name] = {"fs": fs, "seed": 1234, "snr_db": snr_db, "degraded": clean + noise, "clean": clean}
    return cases


def map_crowd_dataset():
    """Seeded crowd-heavy COCO-style dataset (6 images, crowd ratio ~0.4)."""
    rng = np.random.default_rng(77)
    images = []
    for img_id in range(6):
        ng = int(rng.integers(2, 6))
        xy = rng.uniform(0, 120, (ng, 2))
        wh = rng.uniform(10, 80, (ng, 2))
        gb = np.concatenate([xy, xy + wh], axis=1)
        gl = rng.integers(0, 2, ng)
        crowd = (rng.uniform(0, 1, ng) < 0.4).astype(int)
        keep = rng.uniform(0, 1, ng) < 0.9
        pb = gb[keep] + rng.normal(0, 4, (int(keep.sum()), 4))
        pl = gl[keep]
        nfp = int(rng.integers(1, 4))
        fp_xy = rng.uniform(0, 120, (nfp, 2))
        fp_wh = rng.uniform(10, 50, (nfp, 2))
        pb = np.concatenate([pb, np.concatenate([fp_xy, fp_xy + fp_wh], 1)])
        pl = np.concatenate([pl, rng.integers(0, 2, nfp)])
        ps = np.round(rng.uniform(0.1, 1, len(pl)), 6)
        images.append(
            dict(
                image_id=img_id,
                gt_boxes=np.round(gb, 4).tolist(),
                gt_labels=gl.tolist(),
                gt_crowd=crowd.tolist(),
                det_boxes=np.round(pb, 4).tolist(),
                det_labels=pl.tolist(),
                det_scores=ps.tolist(),
            )
        )
    return images


# ------------------------------------------------------------------ generators
def _carry_keys(path: str, out: dict, keys: tuple, defaults: dict) -> None:
    """Preserve committed-fixture metadata keys across regeneration.

    The consuming tests read these (``assert_atol`` drives the tolerance in
    test_stoi_recorded_fixtures.py), so a ``--write`` that dropped them
    would break the very tests the fixture feeds.
    """
    committed = {}
    if os.path.exists(path):
        try:
            committed = json.load(open(path))
        except (OSError, json.JSONDecodeError):
            committed = {}
    for k in keys:
        if k in committed:
            out[k] = committed[k]
        elif k in defaults:
            out[k] = defaults[k]


def gen_stoi(write: bool) -> str:
    path = os.path.join(HERE, "stoi_recorded.json")
    try:
        from pystoi import stoi as pystoi_fn
    except ImportError:
        return "stoi_recorded.json: pystoi not installed — values stay pending"
    cases = stoi_signals()
    out = {"provenance": "pystoi", "tool": "pystoi",
           "tool_version": __import__("pystoi").__version__, "cases": {}}
    for name, c in cases.items():
        val = float(pystoi_fn(c["clean"], c["degraded"], c["fs"], extended=False))
        out["cases"][name] = {"fs": c["fs"], "snr_db": c["snr_db"], "stoi": round(val, 8)}
    _carry_keys(path, out, ("assert_atol", "note"), {"assert_atol": 0.02})
    if write:
        json.dump(out, open(path, "w"), indent=1)
    return f"stoi_recorded.json: generated {len(out['cases'])} values from pystoi"


def gen_map_crowd(write: bool) -> str:
    path = os.path.join(HERE, "map_crowd_recorded.json")
    try:
        from pycocotools.coco import COCO
        from pycocotools.cocoeval import COCOeval
    except ImportError:
        return "map_crowd_recorded.json: pycocotools not installed — values stay pending"
    images = map_crowd_dataset()
    # build COCO gt/dt dicts
    gt = {"images": [{"id": im["image_id"], "height": 300, "width": 300} for im in images],
          "categories": [{"id": 0}, {"id": 1}], "annotations": []}
    dt = []
    ann_id = 1
    for im in images:
        for b, l, c in zip(im["gt_boxes"], im["gt_labels"], im["gt_crowd"]):
            x0, y0, x1, y1 = b
            gt["annotations"].append(
                {"id": ann_id, "image_id": im["image_id"], "category_id": int(l), "iscrowd": int(c),
                 "bbox": [x0, y0, x1 - x0, y1 - y0], "area": (x1 - x0) * (y1 - y0)}
            )
            ann_id += 1
        for b, l, s in zip(im["det_boxes"], im["det_labels"], im["det_scores"]):
            x0, y0, x1, y1 = b
            dt.append({"image_id": im["image_id"], "category_id": int(l),
                       "bbox": [x0, y0, x1 - x0, y1 - y0], "score": float(s)})
    coco_gt = COCO()
    coco_gt.dataset = gt
    coco_gt.createIndex()
    coco_dt = coco_gt.loadRes(dt)
    ev = COCOeval(coco_gt, coco_dt, iouType="bbox")
    ev.evaluate()
    ev.accumulate()
    ev.summarize()
    keys = ["map", "map_50", "map_75", "map_small", "map_medium", "map_large",
            "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large"]
    out = {"provenance": "pycocotools", "tool": "pycocotools", "dataset_seed": 77,
           "expected": {k: round(float(v), 8) for k, v in zip(keys, ev.stats)}}
    _carry_keys(path, out, ("note",), {})
    if write:
        json.dump(out, open(path, "w"), indent=1)
    return "map_crowd_recorded.json: generated from pycocotools COCOeval"


if __name__ == "__main__":
    write = "--write" in sys.argv
    for msg in (gen_stoi(write), gen_map_crowd(write)):
        print(msg)
