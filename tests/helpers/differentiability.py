"""Differentiability harness (VERDICT r4 next #5).

The reference's ``MetricTester.run_differentiability_test`` takes
``torch.autograd.gradcheck`` through ``metric(preds, target)`` for every
metric declaring ``is_differentiable``
(/root/reference/tests/unittests/_helpers/testers.py:531-561).  The JAX
equivalent: ``jax.grad`` of a scalarized ``compute(update(init, *inputs))``
w.r.t. ``preds`` must be finite AND match a central finite difference along
random directions.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

State = dict


def _scalarize(out: Any) -> jnp.ndarray:
    leaves = [
        leaf
        for leaf in jax.tree.leaves(out)
        if hasattr(leaf, "dtype") and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    ]
    assert leaves, "metric produced no float output to differentiate"
    return sum(jnp.sum(jnp.asarray(leaf)) for leaf in leaves)


def assert_differentiable(
    metric_ctor: Callable[[], Any],
    *inputs: Any,
    wrt: int = 0,
    eps: float = 1e-2,
    rtol: float = 5e-2,
    atol: float = 1e-3,
    n_directions: int = 2,
    seed: int = 0,
) -> None:
    """``jax.grad`` through update→compute is finite and matches finite
    differences along ``n_directions`` random unit directions."""
    metric = metric_ctor()
    assert metric.is_differentiable is True, (
        f"{type(metric).__name__} enrolled in the differentiability harness but declares "
        f"is_differentiable={metric.is_differentiable}"
    )
    inputs = tuple(jnp.asarray(x, jnp.float32) if i == wrt else x for i, x in enumerate(inputs))

    def scalar_fn(x):
        args = list(inputs)
        args[wrt] = x
        state = metric.update_state(metric.init_state(), *args)
        return _scalarize(metric.compute_state(state))

    x0 = inputs[wrt]
    grad = jax.grad(scalar_fn)(x0)
    assert np.isfinite(np.asarray(grad)).all(), (
        f"{type(metric).__name__}: non-finite gradient entries"
    )

    f = jax.jit(scalar_fn)
    rng = np.random.default_rng(seed)
    for d in range(n_directions):
        v = rng.normal(size=x0.shape).astype(np.float32)
        v /= np.linalg.norm(v) + 1e-12
        v = jnp.asarray(v)
        fd = (float(f(x0 + eps * v)) - float(f(x0 - eps * v))) / (2 * eps)
        analytic = float(jnp.vdot(grad, v))
        np.testing.assert_allclose(
            analytic,
            fd,
            rtol=rtol,
            atol=atol,
            err_msg=f"{type(metric).__name__}: grad/finite-difference mismatch (direction {d})",
        )


def assert_declared_not_differentiable(metric_ctor: Callable[[], Any]) -> None:
    """Metrics outside the harness must say so explicitly — a None/True claim
    without enrollment is a contract violation (reference testers.py:546)."""
    metric = metric_ctor()
    assert metric.is_differentiable is False, (
        f"{type(metric).__name__}.is_differentiable={metric.is_differentiable}; "
        "non-enrolled metrics must declare False"
    )
