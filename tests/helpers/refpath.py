"""One place that puts the reference implementation + its import stubs on
``sys.path`` for oracle/parity tests (seven test files were each deriving
the relative stubs path by hand)."""

import os
import sys

STUBS_DIR = os.path.abspath(os.path.join(os.path.dirname(__file__), "stubs"))
REFERENCE_SRC = "/root/reference/src"


def add_reference_paths() -> None:
    """Make ``import torchmetrics`` resolve to the reference tree, with the
    lightning_utilities/torchvision/pycocotools stubs it needs."""
    for path in (STUBS_DIR, REFERENCE_SRC):
        if path not in sys.path:
            sys.path.insert(0, path)


def reference_available() -> bool:
    """True when the reference tree is actually mounted."""
    return os.path.isdir(REFERENCE_SRC)


def require_reference() -> None:
    """Module-level gate for reference-parity tests.

    Skips the whole module at collection when the ``/root/reference`` mount
    is absent or the reference's import chain (torch, torchmetrics) doesn't
    resolve — instead of erroring per test in environments without the
    reference checkout.
    """
    import pytest

    if not reference_available():
        pytest.skip(
            f"reference tree not mounted at {REFERENCE_SRC}", allow_module_level=True
        )
    add_reference_paths()
    pytest.importorskip("torch", reason="reference needs torch")
    pytest.importorskip("torchmetrics", reason="reference torchmetrics not importable")
