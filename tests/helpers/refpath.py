"""One place that puts the reference implementation + its import stubs on
``sys.path`` for oracle/parity tests (seven test files were each deriving
the relative stubs path by hand)."""

import os
import sys

STUBS_DIR = os.path.abspath(os.path.join(os.path.dirname(__file__), "stubs"))
REFERENCE_SRC = "/root/reference/src"


def add_reference_paths() -> None:
    """Make ``import torchmetrics`` resolve to the reference tree, with the
    lightning_utilities/torchvision/pycocotools stubs it needs."""
    for path in (STUBS_DIR, REFERENCE_SRC):
        if path not in sys.path:
            sys.path.insert(0, path)
