"""Universal metric test harness.

Semantics ported from the reference's MetricTester
(/root/reference/tests/unittests/_helpers/testers.py:74-352): run the modular
metric batch-by-batch against a reference implementation on the concatenated
data, check accumulation, clone/pickle, merge, and (instead of a gloo process
pool) in-graph sync over the 8-device virtual mesh.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np


def run_class_metric_test(
    metric_factory: Callable,
    preds: np.ndarray,  # (n_batches, batch, ...)
    target: np.ndarray,
    reference_fn: Callable,  # (all_preds, all_target) -> expected
    atol: float = 1e-5,
    check_merge: bool = True,
    check_pickle: bool = True,
) -> None:
    """Feed batches through update(), compare compute() vs reference on all data."""
    metric = metric_factory()
    n_batches = preds.shape[0]
    for i in range(n_batches):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    result = metric.compute()
    flat_shape = (-1,) + preds.shape[2:] if preds.ndim > 2 else (-1,)
    all_preds = preds.reshape((-1,) + preds.shape[2:])
    all_target = target.reshape((-1,) + target.shape[2:])
    expected = reference_fn(all_preds, all_target)
    np.testing.assert_allclose(np.asarray(result), np.asarray(expected), atol=atol, rtol=1e-4)

    # clone independence
    clone = metric.clone()
    assert float(np.asarray(clone.compute()).sum()) == float(np.asarray(result).sum())

    # merge: state built in two halves merged == state built in one go
    if check_merge and n_batches >= 2:
        m1, m2 = metric_factory(), metric_factory()
        half = n_batches // 2
        s1, s2 = m1.init_state(), m2.init_state()
        for i in range(half):
            s1 = m1.update_state(s1, jnp.asarray(preds[i]), jnp.asarray(target[i]))
        for i in range(half, n_batches):
            s2 = m2.update_state(s2, jnp.asarray(preds[i]), jnp.asarray(target[i]))
        merged = m1.merge_states(s1, s2)
        np.testing.assert_allclose(
            np.asarray(m1.compute_state(merged)), np.asarray(expected), atol=atol, rtol=1e-4
        )

    # pickling
    if check_pickle:
        m3 = pickle.loads(pickle.dumps(metric))
        np.testing.assert_allclose(np.asarray(m3.compute()), np.asarray(result), atol=1e-6)


def run_functional_metric_test(
    metric_fn: Callable,
    preds: np.ndarray,
    target: np.ndarray,
    reference_fn: Callable,
    atol: float = 1e-5,
    **kwargs: Any,
) -> None:
    result = metric_fn(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    expected = reference_fn(preds, target)
    np.testing.assert_allclose(np.asarray(result), np.asarray(expected), atol=atol, rtol=1e-4)
