"""Universal sharded-metric tester.

The reference routes *every* metric through a ddp=True ``MetricTester``
(/root/reference/tests/unittests/_helpers/testers.py:352,453): rank-split
updates, state sync, compute, oracle compare.  This is the mesh-native
equivalent: batch-split updates across the 8-virtual-device mesh via
``sharded_update`` (shard_map + in-graph collectives), merge across steps,
compute — asserted identical to single-device accumulation and, when given,
to an external oracle.  One harness, enrolled per domain (VERDICT r3 #4).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np

from torchmetrics_tpu.parallel import sharded_update


def _flatten_result(value: Any) -> dict:
    """Normalize a metric result (array / tuple / dict / nested) to flat
    {path: np.ndarray} for comparison."""
    flat = {}

    def walk(v, path):
        if isinstance(v, dict):
            for k in sorted(v):
                walk(v[k], f"{path}.{k}")
        elif isinstance(v, (tuple, list)):
            for i, e in enumerate(v):
                walk(e, f"{path}[{i}]")
        else:
            flat[path] = np.asarray(v)

    walk(value, "result")
    return flat


def assert_results_close(got: Any, expected: Any, atol: float, rtol: float, label: str) -> None:
    got_flat, exp_flat = _flatten_result(got), _flatten_result(expected)
    assert got_flat.keys() == exp_flat.keys(), (
        f"{label}: result structure differs: {sorted(got_flat)} vs {sorted(exp_flat)}"
    )
    for key in got_flat:
        np.testing.assert_allclose(
            got_flat[key], exp_flat[key], atol=atol, rtol=rtol,
            err_msg=f"{label}: mismatch at {key}",
        )


def assert_sharded_parity(
    mesh,
    metric_ctor: Callable[[], Any],
    batches: Sequence[Tuple[Any, ...]],
    oracle: Optional[Any] = None,
    atol: float = 1e-5,
    rtol: float = 1e-5,
) -> Any:
    """Assert mesh-sharded accumulation ≡ single-device accumulation (≡ oracle).

    ``batches``: per-step input tuples; every array's leading (batch) dim
    must be divisible by the mesh size so ``shard_map`` can split it evenly.
    Returns the single-device result so callers can chain extra checks.
    """
    n_dev = mesh.devices.size
    for step, batch in enumerate(batches):
        for arr in batch:
            assert np.asarray(arr).shape[0] % n_dev == 0, (
                f"batch {step}: leading dim {np.asarray(arr).shape[0]} not divisible by {n_dev}"
            )

    # single-device accumulation (eager facade)
    single = metric_ctor()
    for batch in batches:
        single.update(*batch)
    expected = single.compute()

    # mesh path: shard each step's batch over the devices, sync in-graph,
    # merge the replicated per-step states across steps
    sharded = metric_ctor()
    total = None
    for batch in batches:
        state = sharded_update(sharded, *batch, mesh=mesh)
        total = state if total is None else sharded.merge_states(total, state)
    got = sharded.compute_state(total)
    jax.block_until_ready(jax.tree.leaves(got))

    assert_results_close(got, expected, atol, rtol, label=f"sharded({n_dev})-vs-single")
    if oracle is not None:
        assert_results_close(expected, oracle, atol, rtol, label="single-vs-oracle")
    return expected
