"""Minimal lightning_utilities stub so the reference torchmetrics imports
from /root/reference/src for cross-implementation parity tests.

Only the four names the reference imports are provided (see
`grep "lightning_utilities" -r /root/reference/src/torchmetrics`).
"""
from lightning_utilities.core.apply_func import apply_to_collection  # noqa: F401
