import importlib
import importlib.metadata
import importlib.util
import re


def package_available(name: str) -> bool:
    return importlib.util.find_spec(name) is not None


def module_available(path: str) -> bool:
    if not package_available(path.split(".")[0]):
        return False
    try:
        importlib.import_module(path)
    except Exception:
        return False
    return True


class RequirementCache:
    """Bool-evaluable availability probe for ``pkg`` / ``pkg>=x.y`` requirement strings."""

    def __init__(self, requirement: str, module: str = None) -> None:
        self.requirement = requirement
        self.module = module

    def _check(self) -> bool:
        name = re.split(r"[<>=!~ \[]", self.requirement.strip())[0]
        mod = self.module or name
        if not package_available(mod.replace("-", "_")):
            return False
        cons = self.requirement.strip()[len(name):].strip()
        if not cons:
            return True
        try:
            version = importlib.metadata.version(name)
        except importlib.metadata.PackageNotFoundError:
            return False
        return all(self._cmp(version, c.strip()) for c in cons.split(",") if c.strip())

    @staticmethod
    def _vt(v: str):
        return tuple(int(x) for x in re.findall(r"\d+", v)[:3])

    def _cmp(self, version: str, con: str) -> bool:
        m = re.match(r"(>=|<=|==|<|>|!=)\s*(.+)", con)
        if not m:
            return True
        op, want = m.groups()
        a, b = self._vt(version), self._vt(want)
        return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b, "==": a[:len(b)] == b, "!=": a[:len(b)] != b}[op]

    def __bool__(self) -> bool:
        if not hasattr(self, "_cached"):
            self._cached = self._check()
        return self._cached

    def __str__(self) -> str:
        return f"Requirement '{self.requirement}' {'met' if bool(self) else 'not met'}"

    __repr__ = __str__
