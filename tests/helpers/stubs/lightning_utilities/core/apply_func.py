from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple, Union


def apply_to_collection(
    data: Any,
    dtype: Union[type, Tuple[type, ...]],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, Tuple[type, ...]]] = None,
    include_none: bool = True,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all entries of type ``dtype``."""
    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)
    if isinstance(data, (list, tuple)) and not hasattr(data, "_fields"):
        out = [apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype,
                                   include_none=include_none, **kwargs) for d in data]
        return type(data)(out)
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return type(data)(*(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype,
                                                include_none=include_none, **kwargs) for d in data))
    if isinstance(data, dict):
        return type(data)(
            (k, apply_to_collection(v, dtype, function, *args, wrong_dtype=wrong_dtype,
                                    include_none=include_none, **kwargs))
            for k, v in data.items()
        )
    return data
