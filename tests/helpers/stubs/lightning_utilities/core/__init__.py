from lightning_utilities.core.apply_func import apply_to_collection  # noqa: F401
