from enum import Enum
from typing import Optional


class StrEnum(str, Enum):
    """String enum with case-insensitive lookup (mirror of the public API)."""

    @classmethod
    def from_str(cls, value: str, source: str = "key") -> Optional["StrEnum"]:
        out = cls.try_from_str(value, source=source)
        if out is None:
            raise ValueError(f"Invalid match: expected one of {[e.name for e in cls]}, but got {value}.")
        return out

    @classmethod
    def try_from_str(cls, value: str, source: str = "key") -> Optional["StrEnum"]:
        if source in ("key", "any"):
            for e in cls:
                if e.name.lower() == value.lower():
                    return e
        if source in ("value", "any"):
            for e in cls:
                if e.value.lower() == value.lower():
                    return e
        return None

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Enum):
            other = other.value
        return self.value.lower() == str(other).lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())
