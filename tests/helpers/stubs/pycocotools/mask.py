"""RLE mask utilities are intentionally unimplemented — the bbox oracle path
never calls them; calling means a test wandered into segm territory."""


def area(*args, **kwargs):
    raise NotImplementedError("pycocotools stub: RLE area not available (bbox-only oracle)")


def iou(*args, **kwargs):
    raise NotImplementedError("pycocotools stub: RLE iou not available (bbox-only oracle)")


def decode(*args, **kwargs):
    raise NotImplementedError("pycocotools stub: RLE decode not available (bbox-only oracle)")


def encode(*args, **kwargs):
    raise NotImplementedError("pycocotools stub: RLE encode not available (bbox-only oracle)")
