"""Import-only pycocotools stub: satisfies the reference legacy mAP's
availability probe and module imports for the bbox path (which never calls
RLE mask utilities)."""
