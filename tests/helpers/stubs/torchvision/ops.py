"""torchvision.ops box utilities (exact torch re-implementations)."""

import torch
from torch import Tensor


def box_area(boxes: Tensor) -> Tensor:
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def box_iou(boxes1: Tensor, boxes2: Tensor) -> Tensor:
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = torch.max(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = torch.min(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return inter / union


def box_convert(boxes: Tensor, in_fmt: str, out_fmt: str) -> Tensor:
    if in_fmt == out_fmt:
        return boxes.clone()

    # normalize to xyxy first
    if in_fmt == "xyxy":
        xyxy = boxes
    elif in_fmt == "xywh":
        x, y, w, h = boxes.unbind(-1)
        xyxy = torch.stack([x, y, x + w, y + h], dim=-1)
    elif in_fmt == "cxcywh":
        cx, cy, w, h = boxes.unbind(-1)
        xyxy = torch.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], dim=-1)
    else:
        raise ValueError(f"Unsupported in_fmt {in_fmt}")

    if out_fmt == "xyxy":
        return xyxy
    x1, y1, x2, y2 = xyxy.unbind(-1)
    if out_fmt == "xywh":
        return torch.stack([x1, y1, x2 - x1, y2 - y1], dim=-1)
    if out_fmt == "cxcywh":
        return torch.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], dim=-1)
    raise ValueError(f"Unsupported out_fmt {out_fmt}")
