"""`torchvision.io` stub: transformers' `video_utils.py` imports the module
at import time when torchvision looks installed, but only calls into it when
actually decoding video — which no test here does."""


def _unavailable(*_args, **_kwargs):
    raise RuntimeError("torchvision stub: video/image IO is not available")


read_video = _unavailable
read_image = _unavailable
VideoReader = _unavailable
