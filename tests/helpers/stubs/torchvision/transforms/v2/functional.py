"""`torchvision.transforms.v2.functional` stub: transformers' fast image
processors import the module at import time; every attribute raises if a
test ever actually invokes a torchvision kernel."""


def __getattr__(name):
    raise RuntimeError(
        f"torchvision stub: transforms.v2.functional.{name} is not available "
        "(install real torchvision to use fast image processors)"
    )
