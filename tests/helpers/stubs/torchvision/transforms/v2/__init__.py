"""`torchvision.transforms.v2` stub: re-exports the v1 interpolation enum
(the only symbol availability-probing libraries import at module scope)."""

from torchvision.transforms import InterpolationMode  # noqa: F401
