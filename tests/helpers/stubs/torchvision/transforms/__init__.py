"""`torchvision.transforms` stub — just enough for libraries that probe
torchvision availability via package metadata (the fake dist-info next to
this stub) and then import interpolation enums at module scope.

transformers' `image_utils.py` does `from torchvision.transforms import
InterpolationMode` whenever torchvision looks installed; without this
module the incomplete stub poisoned every transformers model import in the
same process (round-3 regression: 18 parity tests ERROR'd).
"""

import enum


class InterpolationMode(enum.Enum):
    NEAREST = "nearest"
    NEAREST_EXACT = "nearest-exact"
    BILINEAR = "bilinear"
    BICUBIC = "bicubic"
    BOX = "box"
    HAMMING = "hamming"
    LANCZOS = "lanczos"
