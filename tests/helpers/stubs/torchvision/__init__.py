"""Minimal torchvision stub: only the box ops the reference's pure-torch
legacy mAP (`torchmetrics/detection/_mean_ap.py`) needs, so it can run as an
in-image oracle without the real torchvision wheel."""

from torchvision import ops  # noqa: F401

__version__ = "0.15.2"
